"""The vote-record kernel: Snowball confidence tracking, vectorized.

This is layer L0 of the reference (SURVEY.md sections 1, 2.2): the per-target
state machine in `vote.go:24-98`, re-expressed as a branch-free element-wise
update over arrays of any shape — in the simulator, shape ``[nodes, txs]``.
Everything is <=16-bit integer bit-twiddling: shifts, ANDs, SWAR popcounts
(see `ops/bitops.py` for why not `lax.population_count`), and three-way
`where` selects, which XLA fuses into a single VPU pass (there is no
gather/scatter inside the kernel).

State encoding — identical to the reference (`vote.go:25-29, 38-45`):
  votes      : uint8   sliding window of the last 8 votes, bit0 = newest;
               bit set = that vote was a yes            (`vote.go:55`)
  consider   : uint8   sliding window of non-neutral-ness; bit set = that
               vote was NOT an abstention               (`vote.go:56`)
  confidence : uint16  bit 0 = current preference (accepted?); bits 1..15 =
               confidence counter, i.e. isAccepted = confidence & 1
               (`vote.go:38-40`), getConfidence = confidence >> 1
               (`vote.go:43-45`), and "+= 2" bumps the counter by one
               (`vote.go:67`).

Transition, per incoming vote error `err` (`vote.go:54-75`):
  1. shift a yes bit into `votes`, a non-neutral bit into `consider`;
  2. conclusive-yes  iff popcount(votes & consider)  > quorum-1  (>6);
     conclusive-no   iff popcount(~votes & consider) > quorum-1
     (the reference writes ~votes as (-votes-1), `vote.go:61`);
  3. inconclusive -> state unchanged, `changed` = False;
  4. conclusive & agrees with current preference -> counter += 1; `changed`
     is True only at the exact moment the counter hits finalization_score
     (`vote.go:68`: == not >=);
  5. conclusive & disagrees -> preference flips, counter resets to 0
     (`vote.go:72-74`); `changed` = True.

Vote error convention (signed int): 0 = yes, positive = no, negative = neutral
(`vote.go:5`, `vote.go:56`: the uint32 sign-bit test).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from go_avalanche_tpu.config import AvalancheConfig, DEFAULT_CONFIG
from go_avalanche_tpu.ops import swar
from go_avalanche_tpu.ops.bitops import popcount8


class VoteRecordState(NamedTuple):
    """SoA vote-record state; each leaf has the same (arbitrary) shape."""

    votes: jax.Array       # uint8
    consider: jax.Array    # uint8
    confidence: jax.Array  # uint16


def init_state(accepted: jax.Array) -> VoteRecordState:
    """Fresh records seeded with an initial preference (`vote.go:33-35`).

    `accepted` is a bool array of any shape; confidence starts at 0 with the
    preference bit set iff accepted.
    """
    accepted = jnp.asarray(accepted)
    return VoteRecordState(
        votes=jnp.zeros(accepted.shape, jnp.uint8),
        consider=jnp.zeros(accepted.shape, jnp.uint8),
        confidence=accepted.astype(jnp.uint16),
    )


def is_accepted(confidence: jax.Array) -> jax.Array:
    """Preference bit (`vote.go:38-40`)."""
    return (confidence & 1).astype(jnp.bool_)


def get_confidence(confidence: jax.Array) -> jax.Array:
    """Confidence counter (`vote.go:43-45`)."""
    return confidence >> 1


def has_finalized(confidence: jax.Array,
                  cfg: AvalancheConfig = DEFAULT_CONFIG) -> jax.Array:
    """Counter reached the finalization score (`vote.go:48-50`)."""
    return get_confidence(confidence) >= cfg.finalization_score


def status(confidence: jax.Array,
           cfg: AvalancheConfig = DEFAULT_CONFIG) -> jax.Array:
    """Status codes (`vote.go:77-91`), as int8 matching types.Status values."""
    acc = is_accepted(confidence)
    fin = has_finalized(confidence, cfg)
    # finalized: accepted -> FINALIZED(3) else INVALID(0)
    # live:      accepted -> ACCEPTED(2)  else REJECTED(1)
    return jnp.where(
        fin,
        jnp.where(acc, jnp.int8(3), jnp.int8(0)),
        jnp.where(acc, jnp.int8(2), jnp.int8(1)),
    )


def _apply_vote_bits(
    votes: jax.Array,
    consider: jax.Array,
    confidence: jax.Array,
    yes_bit: jax.Array,
    non_neutral_bit: jax.Array,
    cfg: AvalancheConfig,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One window-shift + confidence transition (`vote.go:54-75`).

    The single shared core behind `register_vote` and
    `register_packed_votes`; takes the already-extracted yes / non-neutral
    bits.  Returns (votes, consider, confidence, changed).

    The confidence counter saturates at its 15-bit ceiling instead of wrapping
    (the reference deletes finalized records before overflow could matter,
    `processor.go:114-116`; batched records may live on past finalization, and
    a uint16 wrap would silently un-finalize them).
    """
    window_mask = jnp.uint8((1 << cfg.window) - 1)
    votes = ((votes << 1) | yes_bit.astype(jnp.uint8)) & window_mask
    consider = ((consider << 1)
                | non_neutral_bit.astype(jnp.uint8)) & window_mask

    threshold = jnp.uint8(cfg.quorum - 1)  # reference: > 6 with quorum 7
    yes = popcount8(votes & consider) > threshold
    no = popcount8(jnp.bitwise_not(votes) & consider & window_mask) > threshold
    conclusive = yes | no

    accepted = (confidence & 1) == 1
    agree = accepted == yes

    saturated = get_confidence(confidence) >= jnp.uint16(0x7FFF)
    conf_bumped = jnp.where(saturated, confidence,
                            confidence + jnp.uint16(2))
    conf_reset = yes.astype(jnp.uint16)
    new_confidence = jnp.where(
        conclusive,
        jnp.where(agree, conf_bumped, conf_reset),
        confidence,
    )

    finalized_now = (get_confidence(conf_bumped)
                     == cfg.finalization_score) & agree
    changed = conclusive & (jnp.logical_not(agree) | finalized_now)
    return votes, consider, new_confidence, changed


def register_vote(
    state: VoteRecordState,
    err: jax.Array,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    update_mask: jax.Array | None = None,
) -> Tuple[VoteRecordState, jax.Array]:
    """Apply one vote per record; returns (new_state, changed).

    `err` is a signed integer array broadcastable to the state shape.
    `changed` mirrors the reference's bool return (`vote.go:54`): True iff the
    acceptance or finalization state changed on this vote.

    `update_mask` (bool, optional) freezes records where False — the batched
    replacement for the reference's delete-on-finalize (`processor.go:114-116`)
    and skip-missing-record (`processor.go:95-99`) map operations: masked-out
    records keep their exact state and report changed=False.
    """
    err = jnp.asarray(err)
    votes, consider, confidence, changed = _apply_vote_bits(
        state.votes, state.consider, state.confidence,
        err == 0, err >= 0, cfg)

    if update_mask is not None:
        update_mask = jnp.asarray(update_mask, jnp.bool_)
        votes = jnp.where(update_mask, votes, state.votes)
        consider = jnp.where(update_mask, consider, state.consider)
        confidence = jnp.where(update_mask, confidence, state.confidence)
        changed = changed & update_mask

    return VoteRecordState(votes, consider, confidence), changed


def register_votes_sequence(
    state: VoteRecordState,
    errs: jax.Array,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    update_mask: jax.Array | None = None,
) -> Tuple[VoteRecordState, jax.Array]:
    """Apply a sequence of votes (leading axis of `errs`) via `lax.scan`.

    Returns (final_state, changed[num_votes, ...]).  Mirrors replaying the
    reference ingest loop (`processor.go:94-117`) over a whole response.
    """
    errs = jnp.asarray(errs)

    def step(s, e):
        return register_vote(s, e, cfg, update_mask)

    return lax.scan(step, state, errs)


def register_packed_votes(
    state: VoteRecordState,
    yes_pack: jax.Array,
    consider_pack: jax.Array,
    k: int,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    update_mask: jax.Array | None = None,
    absent_is_skip: bool | None = None,
) -> Tuple[VoteRecordState, jax.Array]:
    """Apply k votes per record from bit-packed planes, oldest-first.

    `yes_pack` / `consider_pack` are uint8 arrays of the state shape; bit j
    (j in [0, k)) holds vote j's yes / non-neutral flag.  Vote 0 is applied
    first.  This is the memory-lean form the simulator uses: the per-round
    gather emits two uint8 planes instead of a [nodes, k, txs] tensor, and the
    k window updates fuse into one element-wise pass (no HBM round-trips
    between them).  Semantically identical to k calls to `register_vote` with
    errs derived from the bits (changed flags are OR-reduced across the k
    votes, which is what one reference response produces at most one status
    update per target from, `processor.go:105-112`).

    `absent_is_skip` selects what a zero consider bit MEANS.  False: a
    DELIVERED neutral vote — it shifts the window with its consider bit
    off, exactly `vote.go:54-75`.  True: a vote that never arrived — the
    slot registers NOTHING (no shift, no confidence transition),
    mirroring the reference HOST path where an expired or missing
    response never reaches RegisterVotes at all (`processor.go:61-122`;
    `response.go:5-51` expiry) and present votes are conclusive.  None
    (the default) reads `cfg.skip_absent_votes`, so every ingest site —
    including the fused/Pallas dispatcher's fallback — follows the
    config with no per-call-site threading; pass a bool to override
    explicitly (tests).  The window-occupancy cost of the False mode is
    quantified in RESULTS.md's churn study.

    Returns (new_state, any_changed).
    """
    if not (0 < k <= 8):
        raise ValueError("k must be in (0, 8] for uint8 packing")

    if absent_is_skip is None:
        absent_is_skip = cfg.skip_absent_votes
    if absent_is_skip:
        return _register_packed_votes_skip(state, yes_pack, consider_pack,
                                           k, cfg, update_mask)

    votes, consider, confidence = state
    any_changed = jnp.zeros(state.votes.shape, jnp.bool_)

    # Hand-fused hot loop.  Semantically identical to k applications of
    # `_apply_vote_bits` (the invariant is pinned by
    # test_packed_votes_match_sequential), but with the per-vote SWAR
    # popcounts replaced by incremental window counters: popcount once
    # before the loop, then +incoming-bit / -evicted-bit per vote.  This
    # roughly halves the VPU op count of the dominant kernel (measured
    # ~6.6ms -> ~3.5ms per round at 8192x8192 on v5e).
    window_mask = jnp.uint8((1 << cfg.window) - 1)
    full_window = cfg.window == 8  # uint8 shifts self-truncate; skip masking
    top_bit = cfg.window - 1
    threshold = jnp.uint8(cfg.quorum - 1)
    one = jnp.uint8(1)

    yes_cnt = popcount8(votes & consider)          # non-neutral yes votes
    cons_cnt = popcount8(consider)                 # non-neutral votes

    for j in range(k):  # unrolled: k is a static config constant
        bit = jnp.uint8(1 << j)
        in_yes_raw = (yes_pack & bit) != 0
        in_cons = ((consider_pack & bit) != 0).astype(jnp.uint8)
        in_yes = in_yes_raw.astype(jnp.uint8) & in_cons  # counted iff considered

        evict_yes = ((votes & consider) >> top_bit) & one
        evict_cons = (consider >> top_bit) & one
        yes_cnt = yes_cnt + in_yes - evict_yes
        cons_cnt = cons_cnt + in_cons - evict_cons

        votes = (votes << 1) | in_yes_raw.astype(jnp.uint8)
        consider = (consider << 1) | in_cons
        if not full_window:
            votes &= window_mask
            consider &= window_mask

        yes = yes_cnt > threshold
        no = (cons_cnt - yes_cnt) > threshold
        conclusive = yes | no

        accepted = (confidence & 1) == 1
        agree = accepted == yes
        saturated = (confidence >> 1) >= jnp.uint16(0x7FFF)
        conf_bumped = jnp.where(saturated, confidence,
                                confidence + jnp.uint16(2))
        confidence = jnp.where(
            conclusive,
            jnp.where(agree, conf_bumped, yes.astype(jnp.uint16)),
            confidence,
        )
        # Counters track votes&consider, which the flip/reset does NOT
        # change (only confidence flips), so no counter fixup is needed.
        finalized_now = ((conf_bumped >> 1) == cfg.finalization_score) & agree
        any_changed |= conclusive & (jnp.logical_not(agree) | finalized_now)

    if not full_window:
        votes &= window_mask
        consider &= window_mask
    new_state = VoteRecordState(votes, consider, confidence)
    if update_mask is not None:
        update_mask = jnp.asarray(update_mask, jnp.bool_)
        new_state = VoteRecordState(
            jnp.where(update_mask, new_state.votes, state.votes),
            jnp.where(update_mask, new_state.consider, state.consider),
            jnp.where(update_mask, new_state.confidence, state.confidence),
        )
        any_changed = any_changed & update_mask
    return new_state, any_changed


def register_packed_votes_engine(
    state: VoteRecordState,
    yes_pack: jax.Array,
    consider_pack: jax.Array,
    k: int,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    update_mask: jax.Array | None = None,
    absent_is_skip: bool | None = None,
) -> Tuple[VoteRecordState, jax.Array]:
    """The ingest-engine dispatch every round implementation calls
    (`models/avalanche`, `models/dag`, `models/snowball`,
    `parallel/sharded*` — the streaming/backlog schedulers inherit it
    through those rounds): `cfg.ingest_engine` selects

      "u8"      — `register_packed_votes`, the golden-parity reference
                  (per-vote uint8 window updates, per-vote confidence
                  fold);
      "swar32"  — `register_packed_votes_swar`, the lane-packed engine
                  (4 tx columns per uint32 word, closed-form confidence
                  transition).

    Both return identical bits on every config axis — pinned by
    tests/test_swar.py the way tests/test_exchange.py pins the
    `cfg.fused_exchange` pair.
    """
    engine = (register_packed_votes_swar if cfg.ingest_engine == "swar32"
              else register_packed_votes)
    return engine(state, yes_pack, consider_pack, k, cfg, update_mask,
                  absent_is_skip)


def _confidence_closed_form(
    confidence: jax.Array,
    outcome16: jax.Array,
    cfg: AvalancheConfig,
) -> Tuple[jax.Array, jax.Array]:
    """The k-vote confidence fold, collapsed to ONE full-width pass.

    `outcome16` is the uint16 combined outcome plane: low byte = the
    yes pack, high byte = the conclusive pack, bit j of each = vote j's
    threshold-yes / conclusiveness — exactly the per-vote `yes` /
    `conclusive` bools of the reference fold (`vote.go:57-75` iterated).
    One combined plane rather than two u8 planes on purpose: XLA's CPU
    backend outlines each output root's backward slice into its own
    parallel fusion WITHOUT multi-output fusion, so a two-plane frontier
    recomputes the whole SWAR vote loop once per plane (measured +25%
    ingest wall at 4096²); a single consumer plane keeps one copy.
    The fold is a run-length process, so it has a closed form:

      * a vote FLIPS iff it is conclusive and disagrees with the current
        preference; since every conclusive vote sets the preference to
        its own `yes`, a trajectory flips at all iff some conclusive
        vote's yes differs from the INITIAL accepted bit a0 — no prefix
        scan needed;
      * the final preference is the LAST conclusive vote's yes (a0 if
        none);
      * the final counter counts the trailing conclusive votes agreeing
        with the final preference: with no flip that run extends the
        incoming counter; with a flip the run's first vote is the flip
        itself (counter := 0) and the rest add one each;
      * `changed` is flips OR a finalization crossing; crossings in a
        post-flip run would need run length >= finalization_score, and
        whenever a post-flip run exists `changed` is already true via
        the flip — so only the no-flip crossing
        ``c0 < score <= c0 + popcount(conclusive)`` is ever decisive.

    Saturation (`counter >= 0x7FFF` stops bumping) is a terminal `min`;
    the one observable corner — finalization_score == 0x7FFF, where the
    reference fold re-reports `changed` on every agreeing vote of an
    already-saturated record — is handled by a statically-gated term.
    Bit-exactness vs the per-vote fold is pinned by the
    tests/test_swar.py property matrix (saturated confidences, tiny and
    maximal finalization scores included).
    """
    u16 = jnp.uint16
    a0 = confidence & 1                       # initial accepted bit, 0/1
    c0 = confidence >> 1                      # incoming counter
    concl = outcome16 >> 8
    yes = (outcome16 & u16(0xFF)) & concl     # only conclusive yes bits count
    has_concl = concl != 0

    # Flip detection: any conclusive yes != a0.
    flips = (concl & (yes ^ (a0 * u16(0xFF)))) != 0

    # Final preference: yes at the highest conclusive bit.
    f = concl | (concl >> 1)
    f |= f >> 2
    f |= f >> 4
    high = f ^ (f >> 1)                       # highest set bit of concl
    a_fin = jnp.where(has_concl, (yes & high) != 0, a0 != 0)

    # Trailing agree-run length: conclusive bits above the last
    # disagreement with the final preference.  D == 0 floods to 0, whose
    # complement is the all-bits mask — the no-disagreement case needs
    # no special path.
    disagree = concl & (yes ^ (a_fin.astype(u16) * u16(0xFF)))
    d = disagree | (disagree >> 1)
    d |= d >> 2
    d |= d >> 4

    def pc8(x):  # popcount of a byte value held in uint16 lanes
        x = x - ((x >> 1) & u16(0x55))
        x = (x & u16(0x33)) + ((x >> 2) & u16(0x33))
        return (x + (x >> 4)) & u16(0x0F)

    run = pc8(concl & (jnp.bitwise_not(d) & u16(0xFF)))
    pc = pc8(concl)

    counter = jnp.where(
        flips,
        run - u16(1),                         # run starts at the flip (:= 0)
        jnp.minimum(c0 + pc, u16(0x7FFF)),    # saturating extension
    )
    new_conf = (counter << 1) | a_fin.astype(u16)

    score = u16(cfg.finalization_score)
    crossed = (c0 < score) & ((c0 + pc) >= score)
    if cfg.finalization_score == 0x7FFF:
        # Saturated records re-report finalization on every agreeing
        # conclusive vote when the score sits AT the saturation ceiling.
        crossed = crossed | ((c0 == u16(0x7FFF)) & (pc > 0))
    changed = flips | crossed
    return new_conf, changed


def register_packed_votes_swar(
    state: VoteRecordState,
    yes_pack: jax.Array,
    consider_pack: jax.Array,
    k: int,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    update_mask: jax.Array | None = None,
    absent_is_skip: bool | None = None,
) -> Tuple[VoteRecordState, jax.Array]:
    """`register_packed_votes` on SWAR lanes: 4 tx columns per uint32.

    Same contract and bit-identical results (tests/test_swar.py); the
    restructuring is pure layout + algebra:

      * `votes`/`consider`/the vote packs and the incremental
        `yes_cnt`/`cons_cnt` counters live as 4 byte lanes per uint32
        word (`ops/swar.py` layout) — the window shift, counter updates
        and quorum compares run lane-parallel at native i32 width, a
        quarter of the elements and ZERO u8->i32 widening on the VPU
        (the exact loss mode of the r03 Pallas kernel);
      * the per-vote quorum outcomes accumulate into two packed outcome
        words (bit j of each lane = vote j), merge into ONE u16 combined
        plane at the engine boundary, and the uint16 confidence plane —
        which cannot lane-pack: its 15-bit counter outgrows a byte lane,
        see PERF_NOTES.md PR 2 — is touched ONCE, by the closed-form
        fold (`_confidence_closed_form`), instead of k times.

    `absent_is_skip` follows `register_packed_votes` exactly; the skip
    mode gates shift/counter/outcome per lane with fill masks instead of
    taking a separate code path.
    """
    if not (0 < k <= 8):
        raise ValueError("k must be in (0, 8] for uint8 packing")
    if absent_is_skip is None:
        absent_is_skip = cfg.skip_absent_votes

    t = state.votes.shape[-1]
    votes_w = swar.pack_u8_lanes(state.votes)
    cons_w = swar.pack_u8_lanes(state.consider)
    yes_w = swar.pack_u8_lanes(jnp.broadcast_to(jnp.asarray(yes_pack),
                                                state.votes.shape))
    pack_w = swar.pack_u8_lanes(jnp.broadcast_to(jnp.asarray(consider_pack),
                                                 state.votes.shape))

    lsb = swar.LANE_LSB
    window_lanes = swar.lane_const((1 << cfg.window) - 1)
    full_window = cfg.window == 8
    top_bit = cfg.window - 1
    threshold = cfg.quorum - 1

    yes_cnt = swar.popcount8_lanes(votes_w & cons_w)
    cons_cnt = swar.popcount8_lanes(cons_w)
    out_yes = jnp.zeros_like(votes_w)
    out_concl = jnp.zeros_like(votes_w)

    for j in range(k):  # unrolled: k is a static config constant
        in_yes_raw = (yes_w >> j) & lsb
        in_cons = (pack_w >> j) & lsb

        if absent_is_skip:
            # Absent slots register NOTHING: gate every delta on the
            # present bit and lane-select the shifted windows.  Present
            # votes shift a set consider bit (every batched responder
            # commits), as in `_register_packed_votes_skip`.
            present = in_cons
            evict_yes = ((votes_w & cons_w) >> top_bit) & present
            evict_cons = (cons_w >> top_bit) & present
            yes_cnt = yes_cnt + (in_yes_raw & present) - evict_yes
            cons_cnt = cons_cnt + present - evict_cons

            shifted_v = swar.lane_shl1(votes_w, in_yes_raw)
            shifted_c = swar.lane_shl1(cons_w, present)
            if not full_window:
                shifted_v &= window_lanes
                shifted_c &= window_lanes
            keep = swar.lane_fill(present)
            votes_w = (shifted_v & keep) | (votes_w & ~keep)
            cons_w = (shifted_c & keep) | (cons_w & ~keep)

            yes_m = swar.lane_gt(yes_cnt, threshold)
            no_m = swar.lane_gt(cons_cnt - yes_cnt, threshold)
            concl_m = (yes_m | no_m) & (present << 7)
        else:
            in_yes = in_yes_raw & in_cons  # counted iff considered
            evict_yes = ((votes_w & cons_w) >> top_bit) & lsb
            evict_cons = (cons_w >> top_bit) & lsb
            yes_cnt = yes_cnt + in_yes - evict_yes
            cons_cnt = cons_cnt + in_cons - evict_cons

            votes_w = swar.lane_shl1(votes_w, in_yes_raw)
            cons_w = swar.lane_shl1(cons_w, in_cons)
            if not full_window:
                votes_w &= window_lanes
                cons_w &= window_lanes

            yes_m = swar.lane_gt(yes_cnt, threshold)
            no_m = swar.lane_gt(cons_cnt - yes_cnt, threshold)
            concl_m = yes_m | no_m

        # Outcome packs: lane MSB masks land on lane bit j.
        out_yes |= yes_m >> (7 - j)
        out_concl |= concl_m >> (7 - j)

    new_votes = swar.unpack_u8_lanes(votes_w, t)
    new_consider = swar.unpack_u8_lanes(cons_w, t)
    outcome16 = ((swar.unpack_u8_lanes(out_concl, t).astype(jnp.uint16) << 8)
                 | swar.unpack_u8_lanes(out_yes, t))
    confidence, any_changed = _confidence_closed_form(
        state.confidence, outcome16, cfg)

    new_state = VoteRecordState(new_votes, new_consider, confidence)
    if update_mask is not None:
        update_mask = jnp.asarray(update_mask, jnp.bool_)
        new_state = VoteRecordState(
            jnp.where(update_mask, new_state.votes, state.votes),
            jnp.where(update_mask, new_state.consider, state.consider),
            jnp.where(update_mask, new_state.confidence, state.confidence),
        )
        any_changed = any_changed & update_mask
    return new_state, any_changed


def register_packed_votes_present(
    state: VoteRecordState,
    yes_pack: jax.Array,
    consider_pack: jax.Array,
    present_pack: jax.Array,
    k: int,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    update_mask: jax.Array | None = None,
) -> Tuple[VoteRecordState, jax.Array]:
    """Three-plane ingest for the async query engine (`ops/inflight.py`).

    Per vote slot j the PRESENT bit selects among three outcomes the
    two-plane form cannot express at once:

      present off              — the slot registers NOTHING (the query is
                                 still in flight, already delivered in an
                                 earlier round, or was never issued);
      present on, consider off — a delivered ABSENCE (a non-response
                                 observed at its scheduled delivery
                                 round, or a timeout expiry under the
                                 delivered-neutral semantics): shifts the
                                 window with its consider bit off,
                                 exactly `vote.go:54-75`;
      present on, consider on  — a real delivered vote.

    With present all-ones this is bit-identical to
    ``register_packed_votes(..., absent_is_skip=False)`` (the fused
    two-plane kernel — pinned transitively by the latency-0 golden
    parity matrix, tests/test_inflight.py); callers wanting
    reference-host skip semantics AND the two-plane present==consider
    collapse simply pass ``present_pack = consider_pack``, which matches
    `_register_packed_votes_skip` (present votes commit a set consider
    bit).  Plain per-slot `_apply_vote_bits` + select: this path runs
    only for async configs, never the flagship bench — clarity over the
    incremental-counter fusion.
    """
    if not (0 < k <= 8):
        raise ValueError("k must be in (0, 8] for uint8 packing")
    votes, consider, confidence = state
    any_changed = jnp.zeros(state.votes.shape, jnp.bool_)
    for j in range(k):
        bit = jnp.uint8(1 << j)
        present = (present_pack & bit) != 0
        yes_bit = (yes_pack & bit) != 0
        cons_bit = (consider_pack & bit) != 0
        v2, c2, conf2, ch2 = _apply_vote_bits(
            votes, consider, confidence, yes_bit, cons_bit, cfg)
        votes = jnp.where(present, v2, votes)
        consider = jnp.where(present, c2, consider)
        confidence = jnp.where(present, conf2, confidence)
        any_changed |= ch2 & present
    new_state = VoteRecordState(votes, consider, confidence)
    if update_mask is not None:
        update_mask = jnp.asarray(update_mask, jnp.bool_)
        new_state = VoteRecordState(
            jnp.where(update_mask, new_state.votes, state.votes),
            jnp.where(update_mask, new_state.consider, state.consider),
            jnp.where(update_mask, new_state.confidence, state.confidence),
        )
        any_changed = any_changed & update_mask
    return new_state, any_changed


def _register_packed_votes_skip(
    state: VoteRecordState,
    yes_pack: jax.Array,
    present_pack: jax.Array,
    k: int,
    cfg: AvalancheConfig,
    update_mask: jax.Array | None,
) -> Tuple[VoteRecordState, jax.Array]:
    """`register_packed_votes` with absent slots registering nothing.

    Plain per-slot `_apply_vote_bits` + select (no incremental-counter
    fusion): this path only activates for configs with non-responses
    (churn / drops / weighted self-draws) under `skip_absent_votes`, never
    for the flagship bench config, so clarity wins over the hand-fused
    form.  Present votes carry non_neutral=True — every batched responder
    commits to a preference; delivered-neutral semantics remain the
    default mode's job.
    """
    votes, consider, confidence = state
    any_changed = jnp.zeros(state.votes.shape, jnp.bool_)
    for j in range(k):
        bit = jnp.uint8(1 << j)
        present = (present_pack & bit) != 0
        yes_bit = (yes_pack & bit) != 0
        v2, c2, conf2, ch2 = _apply_vote_bits(
            votes, consider, confidence, yes_bit,
            jnp.ones_like(yes_bit), cfg)
        votes = jnp.where(present, v2, votes)
        consider = jnp.where(present, c2, consider)
        confidence = jnp.where(present, conf2, confidence)
        any_changed |= ch2 & present
    new_state = VoteRecordState(votes, consider, confidence)
    if update_mask is not None:
        update_mask = jnp.asarray(update_mask, jnp.bool_)
        new_state = VoteRecordState(
            jnp.where(update_mask, new_state.votes, state.votes),
            jnp.where(update_mask, new_state.consider, state.consider),
            jnp.where(update_mask, new_state.confidence, state.confidence),
        )
        any_changed = any_changed & update_mask
    return new_state, any_changed
