"""Pallas TPU kernel: the fused k-vote window update.

The hottest op in the framework (SURVEY.md section 7 hard part (d)): apply k
bit-packed votes per record to the ``[nodes, txs]`` vote-record planes in one
VMEM-resident pass.  Functionally identical to
`voterecord.register_packed_votes` (pinned by tests/test_pallas.py against
the same oracle).

Measured verdict (v5e, jax 0.9.0, 8192x8192, k=8): the XLA-fused jnp path
sustains ~59B votes/s vs ~37B for this kernel.  Mosaic only vectorizes
i16/i32 arithmetic, so the kernel must widen every uint8 plane to int32 —
4x the register/VMEM traffic — while XLA's own fusion keeps the chain in
packed uint8.  A 16-bit variant was also tried (would halve the widening
cost): Mosaic fails to legalize 16-bit vector shifts on this toolchain
(`arith.shrsi`/`arith.shrui` on vector<...xi16> both fail to compile), so
i32 is the narrowest workable width.  The kernel is therefore NOT the default
(`register_packed_votes_fused` prefers the jnp path); it is kept, tested,
and benchmarked as (a) the explicit-kernel reference for the semantics,
(b) insurance against XLA fusion-boundary regressions, and (c) the starting
point if Mosaic grows sub-32-bit arithmetic.

PR 2 takes door (c) from the other side: `_vote_kernel_swar` consumes the
planes PRE-PACKED as SWAR u32 words (4 tx columns per 32-bit lane,
`ops/swar.py`), so the i32 arithmetic width IS the storage width — the 4x
widening traffic that sank this kernel is gone by construction, and the
k-step confidence fold collapses to the closed form
(`voterecord._confidence_closed_form`) run per byte lane.  Confidence
rides as 4 per-lane u16 planes (its 15-bit counter cannot lane-pack); the
body is pure element-wise i32 on same-shaped tiles — no reshapes, no
sub-32-bit vectors — i.e. Mosaic-shaped, but the hardware verdict stays a
ROADMAP item (this container has no TPU; interpreter-mode parity is
pinned by tests/test_pallas.py).

Layout: a 2D grid of (row-block, col-block) tiles.  On non-TPU backends the
kernel runs in interpreter mode (tests), and `register_packed_votes_fused`
falls back to the jnp path for shapes the grid cannot tile.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from go_avalanche_tpu.config import AvalancheConfig, DEFAULT_CONFIG
from go_avalanche_tpu.ops import swar
from go_avalanche_tpu.ops import voterecord as vr

DEFAULT_BLOCK = (64, 512)
# The SWAR kernel's minor dim is words (4 columns each): a (64, 128)-word
# block covers the same (64, 512)-column tile as DEFAULT_BLOCK.
DEFAULT_BLOCK_SWAR = (64, 128)


def _popcount_i32(x: jax.Array) -> jax.Array:
    """SWAR popcount of the low 8 bits, in int32 (Mosaic vectors only
    support i16/i32 arithmetic)."""
    x = x - ((x >> 1) & 0x55)
    x = (x & 0x33) + ((x >> 2) & 0x33)
    return (x + (x >> 4)) & 0x0F


def _vote_kernel(votes_ref, consider_ref, conf_ref, yes_ref, cons_ref,
                 mask_ref, votes_o, consider_o, conf_o, changed_o,
                 *, k: int, cfg: AvalancheConfig) -> None:
    # All arithmetic in int32: the VPU's native lane width, and the only
    # integer vector width (besides i16) Mosaic compiles arithmetic for.
    votes = votes_ref[:].astype(jnp.int32)
    consider = consider_ref[:].astype(jnp.int32)
    confidence = conf_ref[:].astype(jnp.int32)
    yes_pack = yes_ref[:].astype(jnp.int32)
    consider_pack = cons_ref[:].astype(jnp.int32)

    window_mask = (1 << cfg.window) - 1
    top_bit = cfg.window - 1
    threshold = cfg.quorum - 1

    yes_cnt = _popcount_i32(votes & consider)
    cons_cnt = _popcount_i32(consider)
    any_changed = jnp.zeros(votes.shape, jnp.bool_)

    for j in range(k):
        bit = 1 << j
        in_yes_raw = ((yes_pack & bit) != 0).astype(jnp.int32)
        in_cons = ((consider_pack & bit) != 0).astype(jnp.int32)
        in_yes = in_yes_raw & in_cons

        evict_yes = ((votes & consider) >> top_bit) & 1
        evict_cons = (consider >> top_bit) & 1
        yes_cnt = yes_cnt + in_yes - evict_yes
        cons_cnt = cons_cnt + in_cons - evict_cons

        votes = ((votes << 1) | in_yes_raw) & window_mask
        consider = ((consider << 1) | in_cons) & window_mask

        yes = yes_cnt > threshold
        no = (cons_cnt - yes_cnt) > threshold
        conclusive = yes | no

        accepted = (confidence & 1) == 1
        agree = accepted == yes
        saturated = (confidence >> 1) >= 0x7FFF
        conf_bumped = jnp.where(saturated, confidence, confidence + 2)
        confidence = jnp.where(
            conclusive,
            jnp.where(agree, conf_bumped, yes.astype(jnp.int32)),
            confidence,
        )
        finalized_now = ((conf_bumped >> 1) == cfg.finalization_score) & agree
        any_changed |= conclusive & (jnp.logical_not(agree) | finalized_now)

    mask = mask_ref[:].astype(jnp.int32) != 0
    votes_o[:] = jnp.where(mask, votes, votes_ref[:].astype(jnp.int32)
                           ).astype(jnp.uint8)
    consider_o[:] = jnp.where(mask, consider,
                              consider_ref[:].astype(jnp.int32)
                              ).astype(jnp.uint8)
    conf_o[:] = jnp.where(mask, confidence,
                          conf_ref[:].astype(jnp.int32)).astype(jnp.uint16)
    changed_o[:] = (any_changed & mask).astype(jnp.uint8)


def _i32c(value: int) -> int:
    """A 32-bit lane-constant bit pattern as the signed int Python literal
    i32 jnp arithmetic accepts (0x80808080 -> -0x7F7F7F80)."""
    value &= 0xFFFFFFFF
    return value - (1 << 32) if value >= (1 << 31) else value


def _popcount8_i32(x: jax.Array) -> jax.Array:
    """Per-BYTE-LANE popcount on i32 words (4 lanes at once); the masks
    keep every partial inside its lane (`swar.popcount8_lanes` in the
    kernel's i32 domain)."""
    x = x - ((x >> 1) & 0x55555555)
    x = (x & 0x33333333) + ((x >> 2) & 0x33333333)
    return (x + (x >> 4)) & 0x0F0F0F0F


def swar_window_fold(votes, consider, draw_bits, *, k: int,
                     cfg: AvalancheConfig):
    """The shared SWAR window-update loop: k draws of evict / count /
    shift / per-lane quorum compare on pre-packed i32 word tiles.

    ``draw_bits(j) -> (in_yes_raw, in_cons)`` supplies draw j's vote and
    consider bits as lane-LSB i32 words (``& 0x01010101``-shaped values,
    broadcastable against `votes`).  The ingest kernel reads them off its
    pre-packed outcome planes; the whole-round megakernel
    (`ops/megakernel.py`) gathers them from the VMEM-resident preference
    plane — the seam both share so their window semantics can never
    drift.  Returns ``(votes, consider, out_yes, out_concl)`` with the
    per-draw outcomes bit-packed per lane (bit j of lane byte = draw j).

    Every op is element-wise i32 on identically-shaped tiles: no
    reshapes, no sub-32-bit vectors, no strided access — exactly the
    shapes Mosaic vectorizes.  Right shifts on i32 sign-extend; every
    ``>>`` below is followed by a mask that discards the extended bits.
    """
    lsb, msb = 0x01010101, _i32c(0x80808080)
    window_lanes = ((1 << cfg.window) - 1) * lsb
    full_window = cfg.window == 8
    top_bit = cfg.window - 1
    # Bias-to-MSB per-lane compare: lane > threshold (swar.lane_gt).
    gt_bias = (0x7F - (cfg.quorum - 1)) * lsb

    yes_cnt = _popcount8_i32(votes & consider)
    cons_cnt = _popcount8_i32(consider)
    out_yes = jnp.zeros(votes.shape, jnp.int32)
    out_concl = jnp.zeros(votes.shape, jnp.int32)

    for j in range(k):
        in_yes_raw, in_cons = draw_bits(j)
        in_yes = in_yes_raw & in_cons

        evict_yes = ((votes & consider) >> top_bit) & lsb
        evict_cons = (consider >> top_bit) & lsb
        yes_cnt = yes_cnt + in_yes - evict_yes
        cons_cnt = cons_cnt + in_cons - evict_cons

        nocarry = -0x01010102  # 0xFEFEFEFE as i32: drops the <<1 lane carry
        votes = ((votes << 1) & nocarry) | in_yes_raw
        consider = ((consider << 1) & nocarry) | in_cons
        if not full_window:
            votes &= window_lanes
            consider &= window_lanes

        yes_m = (yes_cnt + gt_bias) & msb
        no_m = ((cons_cnt - yes_cnt) + gt_bias) & msb
        concl_m = yes_m | no_m
        lane_bit_j = _i32c(lsb << j)
        out_yes |= (yes_m >> (7 - j)) & lane_bit_j
        out_concl |= (concl_m >> (7 - j)) & lane_bit_j

    return votes, consider, out_yes, out_concl


def swar_confidence_lane(conf, concl, yes, *, cfg: AvalancheConfig):
    """One byte lane of the closed-form confidence fold (the
    `voterecord._confidence_closed_form` algebra on i32 arrays): `conf`
    is the lane's u16 plane widened to i32, `concl`/`yes` the lane's
    bit-packed per-draw outcomes (low 8 bits, draw j at bit j, yes
    already masked conclusive).  Returns ``(new_conf, lane_changed)``
    un-masked — callers apply their own update mask.  Shared verbatim
    by the SWAR ingest kernel and the whole-round megakernel."""
    a0 = conf & 1
    c0 = conf >> 1
    has_concl = concl != 0

    flips = (concl & (yes ^ (a0 * 0xFF))) != 0

    f = concl | (concl >> 1)
    f |= f >> 2
    f |= f >> 4
    high = f ^ (f >> 1)
    a_fin = jnp.where(has_concl, (yes & high) != 0, a0 != 0)

    disagree = concl & (yes ^ (a_fin.astype(jnp.int32) * 0xFF))
    d = disagree | (disagree >> 1)
    d |= d >> 2
    d |= d >> 4
    run = _popcount8_i32(concl & (~d & 0xFF))
    pc = _popcount8_i32(concl)

    counter = jnp.where(flips, run - 1,
                        jnp.minimum(c0 + pc, 0x7FFF))
    new_conf = (counter << 1) | a_fin.astype(jnp.int32)

    score = cfg.finalization_score
    crossed = (c0 < score) & ((c0 + pc) >= score)
    if score == 0x7FFF:
        crossed = crossed | ((c0 == 0x7FFF) & (pc > 0))
    return new_conf, flips | crossed


def swar_confidence_fold(out_yes, out_concl, conf_refs, mask_ref, conf_os,
                         changed_o, *, cfg: AvalancheConfig) -> None:
    """Apply the closed-form fold to all 4 confidence lanes and write the
    masked outputs: the shared tail of the SWAR ingest kernel and the
    megakernel (both produce identical (out_yes, out_concl) packings
    from `swar_window_fold`)."""
    changed_packed = jnp.zeros(out_yes.shape, jnp.int32)
    for lane in range(4):
        conf = conf_refs[lane][:].astype(jnp.int32)
        concl = (out_concl >> (8 * lane)) & 0xFF
        yes = ((out_yes >> (8 * lane)) & 0xFF) & concl
        new_conf, lane_changed = swar_confidence_lane(conf, concl, yes,
                                                      cfg=cfg)
        lane_mask = ((mask_ref[:].astype(jnp.int32) >> (8 * lane)) & 1) != 0
        conf_os[lane][:] = jnp.where(lane_mask, new_conf,
                                     conf).astype(jnp.uint16)
        changed_packed |= ((lane_changed & lane_mask)
                           .astype(jnp.int32) << (8 * lane))
    changed_o[:] = changed_packed.astype(jnp.uint32)


def _vote_kernel_swar(votes_ref, consider_ref, yes_ref, cons_ref, conf_refs,
                      mask_ref, votes_o, consider_o, conf_os, changed_o,
                      *, k: int, cfg: AvalancheConfig) -> None:
    """The SWAR-input kernel body: every plane arrives PRE-PACKED as u32
    words (4 tx columns per word, `ops/swar.py` layout), so the i32
    arithmetic IS the storage width — none of the u8->i32 widening that
    cost the r03 kernel 4x register/VMEM traffic on the window planes.
    Confidence rides as 4 per-lane u16 planes (one per ``t % 4``
    residue, split outside the kernel), each widened 2x to i32 — the
    irreducible remainder, since its 15-bit counter cannot lane-pack
    into a byte.

    The body is `swar_window_fold` reading draw bits off the pre-packed
    outcome planes, plus the shared `swar_confidence_fold` tail — the
    megakernel runs the same two seams with a gathered draw source.
    """
    lsb = 0x01010101
    votes = votes_ref[:].astype(jnp.int32)
    consider = consider_ref[:].astype(jnp.int32)
    yes_w = yes_ref[:].astype(jnp.int32)
    pack_w = cons_ref[:].astype(jnp.int32)

    def draw_bits(j):
        return (yes_w >> j) & lsb, (pack_w >> j) & lsb

    votes, consider, out_yes, out_concl = swar_window_fold(
        votes, consider, draw_bits, k=k, cfg=cfg)

    votes_o[:] = votes.astype(jnp.uint32)
    consider_o[:] = consider.astype(jnp.uint32)
    swar_confidence_fold(out_yes, out_concl, conf_refs, mask_ref, conf_os,
                         changed_o, cfg=cfg)


def register_packed_votes_pallas_swar(
    state: vr.VoteRecordState,
    yes_pack: jax.Array,
    consider_pack: jax.Array,
    k: int,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    update_mask: Optional[jax.Array] = None,
    block: Tuple[int, int] = DEFAULT_BLOCK_SWAR,
    interpret: Optional[bool] = None,
) -> Tuple[vr.VoteRecordState, jax.Array]:
    """The SWAR-input Pallas path: packs the u8 planes to u32 words and
    the confidence plane to 4 per-lane u16 planes OUTSIDE the kernel
    (pure bitcasts/slices XLA fuses into the surrounding program), then
    runs `_vote_kernel_swar` on word tiles.  2D states whose txs axis
    divides by 4 and whose word shape tiles by `block`.

    `interpret` defaults to True off-TPU; on-TPU legalization of this
    body is untested in this container (no TPU — same protocol as the
    r03 kernel: the structure is Mosaic-shaped — pure element-wise i32,
    no reshapes — but the hardware verdict is a ROADMAP item).
    """
    n, t = state.votes.shape
    if t % 4:
        raise ValueError(f"txs axis ({t}) must divide by 4 lanes")
    t4 = t // 4
    bn, bt4 = min(block[0], n), min(block[1], t4)
    if n % bn or t4 % bt4:
        raise ValueError(f"word shape {(n, t4)} does not tile by "
                         f"{(bn, bt4)}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if cfg.skip_absent_votes:
        raise ValueError("the SWAR kernel implements the default "
                         "delivered-neutral consider semantics only "
                         "(dispatchers fall back to the jnp engines)")

    votes_w = swar.pack_u8_lanes(state.votes)
    cons_w = swar.pack_u8_lanes(state.consider)
    yes_w = swar.pack_u8_lanes(jnp.broadcast_to(jnp.asarray(yes_pack),
                                                (n, t)))
    pack_w = swar.pack_u8_lanes(jnp.broadcast_to(jnp.asarray(consider_pack),
                                                 (n, t)))
    mask_u8 = (jnp.ones((n, t), jnp.uint8) if update_mask is None
               else jnp.asarray(update_mask).astype(jnp.uint8))
    mask_w = swar.pack_u8_lanes(mask_u8)
    confs = [state.confidence[:, lane::4] for lane in range(4)]

    spec = pl.BlockSpec((bn, bt4), lambda i, j: (i, j),
                        memory_space=pltpu.VMEM)
    grid = (n // bn, t4 // bt4)

    def kernel(votes_ref, consider_ref, yes_ref, cons_ref,
               c0, c1, c2, c3, mask_ref,
               votes_o, consider_o, o0, o1, o2, o3, changed_o):
        _vote_kernel_swar(votes_ref, consider_ref, yes_ref, cons_ref,
                          (c0, c1, c2, c3), mask_ref, votes_o, consider_o,
                          (o0, o1, o2, o3), changed_o, k=k, cfg=cfg)

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec] * 9,
        out_specs=[spec] * 7,
        out_shape=[
            jax.ShapeDtypeStruct((n, t4), jnp.uint32),
            jax.ShapeDtypeStruct((n, t4), jnp.uint32),
            jax.ShapeDtypeStruct((n, t4), jnp.uint16),
            jax.ShapeDtypeStruct((n, t4), jnp.uint16),
            jax.ShapeDtypeStruct((n, t4), jnp.uint16),
            jax.ShapeDtypeStruct((n, t4), jnp.uint16),
            jax.ShapeDtypeStruct((n, t4), jnp.uint32),
        ],
        interpret=interpret,
    )(votes_w, cons_w, yes_w, pack_w, *confs, mask_w)
    new_votes_w, new_cons_w, o0, o1, o2, o3, changed_w = out

    new_votes = swar.unpack_u8_lanes(new_votes_w, t)
    new_consider = swar.unpack_u8_lanes(new_cons_w, t)
    confidence = jnp.stack([o0, o1, o2, o3], axis=-1).reshape(n, t)
    # The kernel left masked-out confidence untouched per lane; the
    # votes/consider planes restore here (the u8 kernel's `where`, at
    # word width).
    mask_b = mask_u8.astype(jnp.bool_)
    new_votes = jnp.where(mask_b, new_votes, state.votes)
    new_consider = jnp.where(mask_b, new_consider, state.consider)
    changed = swar.expand_lane_mask(changed_w, t)
    return (vr.VoteRecordState(new_votes, new_consider, confidence),
            changed)


def register_packed_votes_pallas(
    state: vr.VoteRecordState,
    yes_pack: jax.Array,
    consider_pack: jax.Array,
    k: int,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    update_mask: Optional[jax.Array] = None,
    block: Tuple[int, int] = DEFAULT_BLOCK,
    interpret: Optional[bool] = None,
) -> Tuple[vr.VoteRecordState, jax.Array]:
    """Pallas path of `voterecord.register_packed_votes` (2D states only).

    Requires the state shape to tile by `block`.  `interpret` defaults to
    True off-TPU so tests exercise the same kernel body everywhere.
    """
    n, t = state.votes.shape
    bn, bt = min(block[0], n), min(block[1], t)
    if n % bn or t % bt:
        raise ValueError(f"shape {(n, t)} does not tile by {(bn, bt)}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    mask = (jnp.ones((n, t), jnp.uint8) if update_mask is None
            else jnp.asarray(update_mask).astype(jnp.uint8))

    spec = pl.BlockSpec((bn, bt), lambda i, j: (i, j),
                        memory_space=pltpu.VMEM)
    grid = (n // bn, t // bt)
    kernel = functools.partial(_vote_kernel, k=k, cfg=cfg)
    votes, consider, confidence, changed = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec] * 6,
        out_specs=[spec] * 4,
        out_shape=[
            jax.ShapeDtypeStruct((n, t), jnp.uint8),
            jax.ShapeDtypeStruct((n, t), jnp.uint8),
            jax.ShapeDtypeStruct((n, t), jnp.uint16),
            jax.ShapeDtypeStruct((n, t), jnp.uint8),
        ],
        interpret=interpret,
    )(state.votes, state.consider, state.confidence, yes_pack,
      consider_pack, mask)
    return (vr.VoteRecordState(votes, consider, confidence),
            changed.astype(jnp.bool_))


def register_packed_votes_fused(
    state: vr.VoteRecordState,
    yes_pack: jax.Array,
    consider_pack: jax.Array,
    k: int,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    update_mask: Optional[jax.Array] = None,
    prefer_pallas: bool = False,
) -> Tuple[vr.VoteRecordState, jax.Array]:
    """Dispatch between the XLA path (default — measured faster, see module
    docstring) and the Pallas kernels (`prefer_pallas=True`, 2D
    block-divisible shapes only).  `cfg.ingest_engine` picks the kernel
    family: "u8" takes the widening kernel, "swar32" the pre-packed u32
    kernel (`register_packed_votes_pallas_swar`)."""
    # The Pallas kernels implement only the default (delivered-neutral)
    # consider semantics; skip_absent_votes configs fall through to the
    # XLA paths, which read the flag from cfg.
    if prefer_pallas and state.votes.ndim == 2 and not cfg.skip_absent_votes:
        n, t = state.votes.shape
        if cfg.ingest_engine == "swar32":
            if t % 4 == 0:
                t4 = t // 4
                bn = min(DEFAULT_BLOCK_SWAR[0], n)
                bt4 = min(DEFAULT_BLOCK_SWAR[1], t4)
                if n % bn == 0 and t4 % bt4 == 0:
                    return register_packed_votes_pallas_swar(
                        state, yes_pack, consider_pack, k, cfg, update_mask)
        else:
            bn, bt = min(DEFAULT_BLOCK[0], n), min(DEFAULT_BLOCK[1], t)
            if n % bn == 0 and t % bt == 0:
                return register_packed_votes_pallas(
                    state, yes_pack, consider_pack, k, cfg, update_mask)
    return vr.register_packed_votes_engine(state, yes_pack, consider_pack,
                                           k, cfg, update_mask)
