"""Pallas TPU kernel: the fused k-vote window update.

The hottest op in the framework (SURVEY.md section 7 hard part (d)): apply k
bit-packed votes per record to the ``[nodes, txs]`` vote-record planes in one
VMEM-resident pass.  Functionally identical to
`voterecord.register_packed_votes` (pinned by tests/test_pallas.py against
the same oracle).

Measured verdict (v5e, jax 0.9.0, 8192x8192, k=8): the XLA-fused jnp path
sustains ~59B votes/s vs ~37B for this kernel.  Mosaic only vectorizes
i16/i32 arithmetic, so the kernel must widen every uint8 plane to int32 —
4x the register/VMEM traffic — while XLA's own fusion keeps the chain in
packed uint8.  A 16-bit variant was also tried (would halve the widening
cost): Mosaic fails to legalize 16-bit vector shifts on this toolchain
(`arith.shrsi`/`arith.shrui` on vector<...xi16> both fail to compile), so
i32 is the narrowest workable width.  The kernel is therefore NOT the default
(`register_packed_votes_fused` prefers the jnp path); it is kept, tested,
and benchmarked as (a) the explicit-kernel reference for the semantics,
(b) insurance against XLA fusion-boundary regressions, and (c) the starting
point if Mosaic grows sub-32-bit arithmetic.

Layout: a 2D grid of (row-block, col-block) tiles.  On non-TPU backends the
kernel runs in interpreter mode (tests), and `register_packed_votes_fused`
falls back to the jnp path for shapes the grid cannot tile.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from go_avalanche_tpu.config import AvalancheConfig, DEFAULT_CONFIG
from go_avalanche_tpu.ops import voterecord as vr

DEFAULT_BLOCK = (64, 512)


def _popcount_i32(x: jax.Array) -> jax.Array:
    """SWAR popcount of the low 8 bits, in int32 (Mosaic vectors only
    support i16/i32 arithmetic)."""
    x = x - ((x >> 1) & 0x55)
    x = (x & 0x33) + ((x >> 2) & 0x33)
    return (x + (x >> 4)) & 0x0F


def _vote_kernel(votes_ref, consider_ref, conf_ref, yes_ref, cons_ref,
                 mask_ref, votes_o, consider_o, conf_o, changed_o,
                 *, k: int, cfg: AvalancheConfig) -> None:
    # All arithmetic in int32: the VPU's native lane width, and the only
    # integer vector width (besides i16) Mosaic compiles arithmetic for.
    votes = votes_ref[:].astype(jnp.int32)
    consider = consider_ref[:].astype(jnp.int32)
    confidence = conf_ref[:].astype(jnp.int32)
    yes_pack = yes_ref[:].astype(jnp.int32)
    consider_pack = cons_ref[:].astype(jnp.int32)

    window_mask = (1 << cfg.window) - 1
    top_bit = cfg.window - 1
    threshold = cfg.quorum - 1

    yes_cnt = _popcount_i32(votes & consider)
    cons_cnt = _popcount_i32(consider)
    any_changed = jnp.zeros(votes.shape, jnp.bool_)

    for j in range(k):
        bit = 1 << j
        in_yes_raw = ((yes_pack & bit) != 0).astype(jnp.int32)
        in_cons = ((consider_pack & bit) != 0).astype(jnp.int32)
        in_yes = in_yes_raw & in_cons

        evict_yes = ((votes & consider) >> top_bit) & 1
        evict_cons = (consider >> top_bit) & 1
        yes_cnt = yes_cnt + in_yes - evict_yes
        cons_cnt = cons_cnt + in_cons - evict_cons

        votes = ((votes << 1) | in_yes_raw) & window_mask
        consider = ((consider << 1) | in_cons) & window_mask

        yes = yes_cnt > threshold
        no = (cons_cnt - yes_cnt) > threshold
        conclusive = yes | no

        accepted = (confidence & 1) == 1
        agree = accepted == yes
        saturated = (confidence >> 1) >= 0x7FFF
        conf_bumped = jnp.where(saturated, confidence, confidence + 2)
        confidence = jnp.where(
            conclusive,
            jnp.where(agree, conf_bumped, yes.astype(jnp.int32)),
            confidence,
        )
        finalized_now = ((conf_bumped >> 1) == cfg.finalization_score) & agree
        any_changed |= conclusive & (jnp.logical_not(agree) | finalized_now)

    mask = mask_ref[:].astype(jnp.int32) != 0
    votes_o[:] = jnp.where(mask, votes, votes_ref[:].astype(jnp.int32)
                           ).astype(jnp.uint8)
    consider_o[:] = jnp.where(mask, consider,
                              consider_ref[:].astype(jnp.int32)
                              ).astype(jnp.uint8)
    conf_o[:] = jnp.where(mask, confidence,
                          conf_ref[:].astype(jnp.int32)).astype(jnp.uint16)
    changed_o[:] = (any_changed & mask).astype(jnp.uint8)


def register_packed_votes_pallas(
    state: vr.VoteRecordState,
    yes_pack: jax.Array,
    consider_pack: jax.Array,
    k: int,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    update_mask: Optional[jax.Array] = None,
    block: Tuple[int, int] = DEFAULT_BLOCK,
    interpret: Optional[bool] = None,
) -> Tuple[vr.VoteRecordState, jax.Array]:
    """Pallas path of `voterecord.register_packed_votes` (2D states only).

    Requires the state shape to tile by `block`.  `interpret` defaults to
    True off-TPU so tests exercise the same kernel body everywhere.
    """
    n, t = state.votes.shape
    bn, bt = min(block[0], n), min(block[1], t)
    if n % bn or t % bt:
        raise ValueError(f"shape {(n, t)} does not tile by {(bn, bt)}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    mask = (jnp.ones((n, t), jnp.uint8) if update_mask is None
            else jnp.asarray(update_mask).astype(jnp.uint8))

    spec = pl.BlockSpec((bn, bt), lambda i, j: (i, j),
                        memory_space=pltpu.VMEM)
    grid = (n // bn, t // bt)
    kernel = functools.partial(_vote_kernel, k=k, cfg=cfg)
    votes, consider, confidence, changed = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec] * 6,
        out_specs=[spec] * 4,
        out_shape=[
            jax.ShapeDtypeStruct((n, t), jnp.uint8),
            jax.ShapeDtypeStruct((n, t), jnp.uint8),
            jax.ShapeDtypeStruct((n, t), jnp.uint16),
            jax.ShapeDtypeStruct((n, t), jnp.uint8),
        ],
        interpret=interpret,
    )(state.votes, state.consider, state.confidence, yes_pack,
      consider_pack, mask)
    return (vr.VoteRecordState(votes, consider, confidence),
            changed.astype(jnp.bool_))


def register_packed_votes_fused(
    state: vr.VoteRecordState,
    yes_pack: jax.Array,
    consider_pack: jax.Array,
    k: int,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    update_mask: Optional[jax.Array] = None,
    prefer_pallas: bool = False,
) -> Tuple[vr.VoteRecordState, jax.Array]:
    """Dispatch between the XLA path (default — measured faster, see module
    docstring) and the Pallas kernel (`prefer_pallas=True`, 2D
    block-divisible shapes only)."""
    # The Pallas kernel implements only the default (delivered-neutral)
    # consider semantics; skip_absent_votes configs fall through to the
    # XLA path, which reads the flag from cfg.
    if prefer_pallas and state.votes.ndim == 2 and not cfg.skip_absent_votes:
        n, t = state.votes.shape
        bn, bt = min(DEFAULT_BLOCK[0], n), min(DEFAULT_BLOCK[1], t)
        if n % bn == 0 and t % bt == 0:
            return register_packed_votes_pallas(
                state, yes_pack, consider_pack, k, cfg, update_mask)
    return vr.register_packed_votes(state, yes_pack, consider_pack, k, cfg,
                                    update_mask)
