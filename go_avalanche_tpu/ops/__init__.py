"""Vectorized consensus kernels (layer L0)."""
