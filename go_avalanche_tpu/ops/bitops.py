"""Small integer bit-twiddling helpers shared by the kernels.

`popcount8` replaces `lax.population_count` on uint8 because of a verified
XLA:CPU miscompile: inside the fused vote-update loop at certain batch widths
(observed at batch=64 under `lax.scan`, jax 0.9.0), the vectorized uint8
popcount of `~votes & consider` returns values off by one (e.g. 7 for
0b11011011).  The SWAR form below is four VPU-cheap arithmetic ops, compiles
correctly on every backend, and is what the reference's Kernighan loop
(`vote.go:93-98`) becomes when vectorized.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def popcount8(x: jax.Array) -> jax.Array:
    """Per-element popcount of a uint8 array (SWAR, branch-free)."""
    x = x - ((x >> 1) & jnp.uint8(0x55))
    x = (x & jnp.uint8(0x33)) + ((x >> 2) & jnp.uint8(0x33))
    return (x + (x >> 4)) & jnp.uint8(0x0F)
