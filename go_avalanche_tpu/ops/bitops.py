"""Small integer bit-twiddling helpers shared by the kernels.

`popcount8` replaces `lax.population_count` on uint8 because of a verified
miscompile on the TPU (axon) backend, jax 0.9.0: inside the fused vote-update
loop at certain batch widths (observed at batch=64 under `lax.scan`), the
vectorized uint8 popcount of `~votes & consider` returns values off by one
(e.g. 7 for 0b11011011).  The same program is correct on the XLA:CPU backend.
The SWAR form below is four VPU-cheap arithmetic ops, compiles correctly on
every backend, and is what the reference's Kernighan loop (`vote.go:93-98`)
becomes when vectorized.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def popcount8(x: jax.Array) -> jax.Array:
    """Per-element popcount of a uint8 array (SWAR, branch-free)."""
    x = x - ((x >> 1) & jnp.uint8(0x55))
    x = (x & jnp.uint8(0x33)) + ((x >> 2) & jnp.uint8(0x33))
    return (x + (x >> 4)) & jnp.uint8(0x0F)


def pack_bool_plane(x: jax.Array) -> jax.Array:
    """Pack a bool ``[..., t]`` plane into uint8 ``[..., ceil(t/8)]``, bit j
    of byte b holding column ``8*b + j``.  The wire format for cross-shard
    preference exchange: 8x less all-gather traffic than bool planes.
    Leading dimensions pass through (the fused exchange engine packs
    ``[n, k, t]`` vote cubes with the same layout)."""
    *lead, t = x.shape
    tp = -(-t // 8) * 8
    if tp != t:
        x = jnp.pad(x, [(0, 0)] * len(lead) + [(0, tp - t)])
    shifts = jnp.arange(8, dtype=jnp.uint8)
    return (x.reshape(*lead, tp // 8, 8).astype(jnp.uint8) << shifts).sum(
        axis=-1).astype(jnp.uint8)


def unpack_bool_plane(packed: jax.Array, t: int) -> jax.Array:
    """Inverse of `pack_bool_plane`: uint8 ``[..., ceil(t/8)]`` -> bool
    ``[..., t]``.  Pure element-wise bit extraction, so on a gathered
    ``[n, k, ceil(t/8)]`` cube XLA fuses it into whatever consumes the
    bools — no unpacked cube ever lands in HBM."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (packed[..., None] >> shifts) & jnp.uint8(1)
    return bits.reshape(*packed.shape[:-1], -1)[..., :t].astype(jnp.bool_)
