"""The in-flight query engine: response latency, timeout expiry, and
partition faults for the batched simulators.

The reference Processor is fundamentally ASYNCHRONOUS: `event_loop` records
an outstanding query per poll (`processor.go:235-243`), responses arrive
whenever the network delivers them, and requests older than
`request_timeout_s` are reaped unanswered (`processor.go:61-122`,
`response.go:49-51` — honored today by the host twin `processor.py` only).
Every batched model, by contrast, resolved its k polls instantaneously
within the issuing round, so `cfg.request_timeout_s` was dead config on
the scale path and the only network fault was a memoryless drop.  Liveness
under message delay is qualitatively different from the synchronous ideal
(arXiv:2409.02217 quantifies Snowball liveness under partial synchrony;
TangleSim, arXiv:2305.01232, treats network latency as a first-class
simulation axis) — this module gives the `[N, T]` models that axis.

Mechanics — everything fixed-shape, `lax.scan`/`while_loop`-compatible,
no host round-trips:

  * each round's k polls per node are ENQUEUED into a depth-
    ``timeout_rounds() + 1`` ring buffer of pending-query planes carried
    in the sim state (`InflightState`), stamped with a per-(querier,
    draw) latency in rounds drawn by `draw_latency`
    (`cfg.latency_mode`: fixed / geometric / coupled to the
    `latency_weight` plane);
  * the DELIVERY pass (`deliver_multi` / `deliver_1d`) walks the ring
    oldest-age-first each round: an entry whose latency equals its age
    gathers the responder's CURRENT preference (responses reflect
    responder state at answer time; the query/transmission leg is
    instantaneous, which keeps gossip-on-poll at issue time) and ingests
    through the three-plane kernel
    (`voterecord.register_packed_votes_present`);
  * entries still undelivered at age `cfg.timeout_rounds()` EXPIRE
    UNANSWERED — exactly the host Processor's reaping
    (`processor.py:262-269`): under `cfg.skip_absent_votes` they
    register nothing (reference-host semantics, an expired response
    never reaches RegisterVotes), otherwise they shift the window as a
    delivered neutral, the same absence semantics drops get;
  * the FAULT-SCRIPT engine (`cfg.fault_script`, with `partition_spec`
    as the one-event sugar) applies at ISSUE time: latency_spike events
    add rounds to the drawn latency (`apply_latency_spikes`), and cut
    events — partitions and regional outages — mark severed draws
    undeliverable (`partition_cut` -> the timeout sentinel), so those
    queries time out rather than silently vanishing and a healed cut
    shows the timeout tail, not an instant recovery; churn_burst events
    are one-shot alive-toggle impulses applied by the models' churn
    stage (`apply_churn_bursts`).  Every event window is jit-static:
    the script compiles into per-round masks gated by scalar
    round-range tests, and an empty script is statically absent (every
    archived hlo pin byte-identical — `hlo_pin.py --verify-off-path`);
  * `latency_mode="rtt"` draws topology-coupled latency from the
    static C x C `cfg.rtt_matrix` over the clustered topology's
    contiguous-block clusters — per-(querier, responder) latency
    without an O(N^2) plane.

Latency-0 (`latency_mode="fixed"`, `latency_rounds=0`) is bit-exact with
the synchronous round on every model and config axis
(tests/test_inflight.py golden parity): the just-enqueued entry delivers
in the same round, reading the same pre-round preference plane with the
same PRNG keys.  With `cfg.async_queries()` False the engine is
statically absent (state leaf None, zero trace impact — the flagship
`hlo_pin` hash is unchanged).

Delivery engines (`cfg.inflight_engine`, PR 4) — all bit-exact twins:

  walk          — the reference pass above: `lax.fori_loop` over every
                  ring age (compiled size O(1) in depth, runtime and
                  state round-trips O(depth));
  walk_earlyout — the same walk with a per-age `lax.cond` that skips
                  ages whose slot has no deliverable/expiring entry
                  (the gathers, the adversary transform and the k-vote
                  ingest all sit inside the cond) — the cheap win when
                  latency << timeout leaves most ages inert;
  coalesced     — ONE ring drain (`deliver_multi_coalesced` /
                  `deliver_1d_coalesced`): the deliverable mask is
                  computed for the whole ``[D, rows, k]`` ring at once
                  (no T axis) and reduced to a per-age activity flag;
                  only ACTIVE ages then pay their gather + present-
                  masked ingest, in the walk's exact order (oldest age
                  first, then draw) — under fixed latency the active
                  age is even known statically, making the drain's
                  cost proportional to deliveries rather than ring
                  depth.  Multi-age collisions (two entries in the same
                  draw slot delivering the same round) land in the same
                  sequence the walk applies them, and the
                  finalized-mid-flight freeze re-reads confidence at
                  every age boundary exactly where the walk's per-age
                  `update_mask` reads it.  The coalesced ring also
                  stores its poll-mask plane BIT-PACKED
                  (`packed_polled_width`): per-shard tx widths pad up
                  to the next byte multiple, which is what lets the
                  packed plane shard over the txs axis at widths that
                  are not multiples of 8 (the PR 3 blocker).
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from go_avalanche_tpu.config import AvalancheConfig
from go_avalanche_tpu.ops import adversary, exchange, voterecord as vr
from go_avalanche_tpu.ops.bitops import (
    pack_bool_plane,
    popcount8,
    unpack_bool_plane,
)

# fold_in constant deriving the latency stream from the round's sampling
# key: the latency draw must not perturb any existing stream (latency-0
# trajectories are pinned bit-exact against the synchronous round).
_LAT_FOLD = 0x1A7E

# fold_in constant deriving the stochastic fault-parameter stream from
# the sim's INIT key (`draw_fault_params`): realized schedules must be a
# pure function of (config, init key) — one draw per sim, constant
# across rounds, never perturbing the per-round streams.
_FAULT_PARAM_FOLD = 0x57CA


class FaultParams(NamedTuple):
    """Realized parameters of the config's STOCHASTIC fault events
    (`cfg.stochastic_events()`), drawn once per sim by
    `draw_fault_params` from the init key and carried in the sim state
    (`state.fault_params`; None — statically absent — when the script
    schedules no stochastic events, so every archived hlo pin is
    untouched).

    Event STRUCTURE stays jit-static: the arrays below are indexed by
    the script's stochastic-event order with static lengths, so each
    realized event still compiles to one mask AND'd with a (now traced)
    round-range test — a different realization per fleet trial under
    `vmap`, one compiled program for all of them.
    """

    cut_start: jax.Array    # int32 [Ec] — stochastic_partition starts
    cut_end: jax.Array      # int32 [Ec] — end-exclusive heals
    cut_split: jax.Array    # int32 [Ec] — realized node-split index
                            #   (cluster-aligned when n_clusters > 1)
    spike_start: jax.Array  # int32 [Es] — stochastic_spike starts
    spike_end: jax.Array    # int32 [Es]
    spike_extra: jax.Array  # int32 [Es] — realized extra rounds
    region_start: jax.Array  # int32 [Er] — stochastic_regional_outage
    region_end: jax.Array    #   realized windows (end-exclusive)
    region_cluster: jax.Array  # int32 [Er] — the realized severed
                            #   cluster, drawn from the event's
                            #   [lo, hi] cluster range


def _stochastic_split(cfg: AvalancheConfig, n_global: int,
                      frac: jax.Array) -> jax.Array:
    """Traced twin of `_partition_split`: node-split index of a realized
    partition fraction — same floor(x+0.5) cluster snap, same interior
    clamps, on a traced `frac` scalar."""
    if cfg.n_clusters > 1:
        c = jnp.clip(jnp.floor(frac * cfg.n_clusters + 0.5)
                     .astype(jnp.int32), 1, cfg.n_clusters - 1)
        return (c * n_global + cfg.n_clusters - 1) // cfg.n_clusters
    return jnp.clip(jnp.floor(frac * n_global).astype(jnp.int32),
                    1, n_global - 1)


def draw_fault_params(cfg: AvalancheConfig, key: jax.Array,
                      n_global: int) -> Optional[FaultParams]:
    """Realize the config's stochastic fault events from the sim's init
    key; None (statically) when the script schedules none.

    Per event (the `cfg.stochastic_events()` order), from an
    independent fold of `key`: start ~ U{lo..hi}, length ~ U{lo..hi}
    (end = start + length, end-exclusive), and the kind's parameter —
    frac ~ U(lo, hi) resolved to a cluster-aligned split index
    (`_stochastic_split`), or extra_rounds ~ U{lo..hi}.  Deterministic:
    the same (config, key) always realizes the same schedule, dense or
    sharded (the sharded drivers carry the SAME replicated params the
    dense init drew).
    """
    events = cfg.stochastic_events()
    if not events:
        return None
    key = jax.random.fold_in(key, _FAULT_PARAM_FOLD)
    cut = {"start": [], "end": [], "split": []}
    spike = {"start": [], "end": [], "extra": []}
    region = {"start": [], "end": [], "cluster": []}
    for i, ev in enumerate(events):
        ks, kl, kp = jax.random.split(jax.random.fold_in(key, i), 3)
        (slo, shi), (llo, lhi) = ev[1], ev[2]
        start = jax.random.randint(ks, (), int(slo), int(shi) + 1,
                                   dtype=jnp.int32)
        length = jax.random.randint(kl, (), int(llo), int(lhi) + 1,
                                    dtype=jnp.int32)
        if ev[0] == "stochastic_partition":
            flo, fhi = ev[3]
            frac = jax.random.uniform(kp, (), minval=float(flo),
                                      maxval=float(fhi))
            cut["start"].append(start)
            cut["end"].append(start + length)
            cut["split"].append(_stochastic_split(cfg, n_global, frac))
        elif ev[0] == "stochastic_regional_outage":
            clo, chi = ev[3]
            region["start"].append(start)
            region["end"].append(start + length)
            region["cluster"].append(jax.random.randint(
                kp, (), int(clo), int(chi) + 1, dtype=jnp.int32))
        else:                                   # stochastic_spike
            elo, ehi = ev[3]
            spike["start"].append(start)
            spike["end"].append(start + length)
            spike["extra"].append(jax.random.randint(
                kp, (), int(elo), int(ehi) + 1, dtype=jnp.int32))

    def stack(xs):
        return jnp.stack(xs) if xs else jnp.zeros((0,), jnp.int32)

    return FaultParams(cut_start=stack(cut["start"]),
                       cut_end=stack(cut["end"]),
                       cut_split=stack(cut["split"]),
                       spike_start=stack(spike["start"]),
                       spike_end=stack(spike["end"]),
                       spike_extra=stack(spike["extra"]),
                       region_start=stack(region["start"]),
                       region_end=stack(region["end"]),
                       region_cluster=stack(region["cluster"]))


class InflightState(NamedTuple):
    """Ring buffer of pending queries; a pytree of ``[D, rows, ...]``
    planes (D = ``cfg.timeout_rounds() + 1``; rows = N, or n_local on a
    shard).  Slot ``r % D`` holds the queries ISSUED in round r; an
    entry's age in round ``r'`` is ``r' - r``, and the slot is
    overwritten exactly one round after its entries expire.

    `polled` is the issue-time update mask.  Multi-target models: bool
    ``[D, rows, T]`` for the walk engines (the PR 3 layout, kept
    verbatim so the `flagship_async` pin never moves), uint8
    ``[D, rows, packed_polled_width(T, tx_shards)]`` BIT-PACKED for the
    coalesced engine — each tx shard's width pads up to the next byte
    multiple, which is what lets the packed plane shard over the txs
    axis when the per-shard width is not a multiple of 8 (the PR 3
    blocker).  Single-decree Snowball: bool ``[D, rows]`` always.
    `lat` is clipped to ``[0, timeout_rounds()]``; the top value is the
    NEVER-delivers sentinel (expires unanswered).
    """

    peers: jax.Array      # int32 [D, rows, k] — global peer ids
    lat: jax.Array        # int32 [D, rows, k] — delivery age; == timeout
                          #   sentinel means "expires unanswered"
    responded: jax.Array  # bool [D, rows, k] — issue-time alive/drop/self
    lie: jax.Array        # bool [D, rows, k] — issue-time adversary mask
    polled: jax.Array     # walk engines: bool [D, rows, T]; coalesced:
                          #   uint8 [D, rows, packed_polled_width(...)]
                          #   (bit-packed — see class docstring); bool
                          #   [D, rows] for snowball either way


def enabled(cfg: AvalancheConfig) -> bool:
    """Static: is the in-flight engine on for this config?"""
    return cfg.async_queries()


def ring_depth(cfg: AvalancheConfig) -> int:
    """Slots in the ring: ages ``0 .. timeout_rounds()`` inclusive."""
    return cfg.timeout_rounds() + 1


def packed_polled_width(t: int, tx_shards: int = 1) -> int:
    """Bytes in the coalesced engine's bit-packed poll-mask plane.

    Each of the `tx_shards` contiguous tx blocks packs its own
    ``t / tx_shards`` columns into ``ceil(t_local / 8)`` bytes — padding
    every PER-SHARD width to a byte multiple, so the packed plane's
    byte axis splits evenly over the txs mesh axis no matter the local
    width.  With one shard this is plain ``ceil(t / 8)``.
    """
    if tx_shards < 1 or t % tx_shards:
        raise ValueError(f"t={t} must divide into tx_shards={tx_shards}")
    return tx_shards * (-(-(t // tx_shards) // 8))


def repack_polled_for_shards(ring: Optional[InflightState], t: int,
                             tx_shards: int) -> Optional[InflightState]:
    """Re-layout a host-built packed ring for a tx-sharded mesh.

    Model `init` packs the poll-mask plane with the single-shard layout
    (``ceil(t/8)`` bytes); placing that state on a mesh whose per-shard
    width is not a byte multiple needs the per-shard-padded layout
    instead.  The input MUST carry the 1-shard layout (every model
    `init` does) — unpacks it and repacks per shard block, lossless.
    No-op when the 1-shard layout already IS the per-shard layout
    (``t/tx_shards`` a byte multiple) or the ring is unpacked (walk
    engines) / absent.  The layout test is on ALIGNMENT, not byte
    width: at e.g. t=26 over 2 shards both layouts occupy 4 bytes yet
    place columns differently, so equal widths prove nothing.
    """
    if ring is None or ring.polled.dtype != jnp.uint8:
        return ring
    if tx_shards == 1 or (t // tx_shards) % 8 == 0:
        return ring
    pw = packed_polled_width(t, tx_shards)
    lead = ring.polled.shape[:-1]
    unpacked = unpack_bool_plane(ring.polled, t)
    blocks = unpacked.reshape(*lead, tx_shards, t // tx_shards)
    return ring._replace(
        polled=pack_bool_plane(blocks).reshape(*lead, pw))


def init_ring(cfg: AvalancheConfig, rows: int,
              t: Optional[int] = None,
              tx_shards: int = 1) -> InflightState:
    """Empty ring: every slot pre-expired (lat = sentinel) with an
    all-zero update mask, so untouched slots never register anything.

    The poll-mask plane's layout follows `cfg.inflight_engine`: bool
    ``[D, rows, t]`` for the walk engines (PR 3 verbatim), bit-packed
    uint8 ``[D, rows, packed_polled_width(t, tx_shards)]`` for the
    coalesced engine (`tx_shards` > 1 pads per-shard widths for a
    tx-sharded mesh — `repack_polled_for_shards` fixes up host-built
    states after the fact).
    """
    d = ring_depth(cfg)
    k = cfg.k
    if t is None:            # single-decree: per-node bool mask
        polled = jnp.zeros((d, rows), jnp.bool_)
    elif cfg.inflight_engine == "coalesced":
        polled = jnp.zeros((d, rows, packed_polled_width(t, tx_shards)),
                           jnp.uint8)
    else:                    # multi-target: per-(node, tx) bool mask
        polled = jnp.zeros((d, rows, t), jnp.bool_)
    return InflightState(
        peers=jnp.zeros((d, rows, k), jnp.int32),
        lat=jnp.full((d, rows, k), cfg.timeout_rounds(), jnp.int32),
        responded=jnp.zeros((d, rows, k), jnp.bool_),
        lie=jnp.zeros((d, rows, k), jnp.bool_),
        polled=polled,
    )


def _cluster_of(ids: jax.Array, n_clusters: int,
                n_global: int) -> jax.Array:
    """Cluster of each global node id — `ops/sampling.cluster_of`, THE
    one spelling of the clustered topology's partition (``i * C // N``,
    contiguous blocks, derived, never stored): the cluster an outage
    severs / an RTT row indexes is exactly the cluster the sampler
    draws from."""
    from go_avalanche_tpu.ops.sampling import cluster_of

    return cluster_of(ids, n_clusters, n_global)


def draw_latency(
    key: jax.Array,
    cfg: AvalancheConfig,
    peers: jax.Array,
    latency_weight: jax.Array,
    n_global: int,
    row_offset=0,
) -> jax.Array:
    """Per-(querier, draw) response latency in rounds; int32 ``[rows, k]``
    clipped to ``[0, timeout_rounds()]`` (the top value never delivers).

    fixed     — every draw takes `cfg.latency_rounds`.
    geometric — iid Geometric on {0, 1, ...} with mean `latency_rounds`
                (success prob p = 1/(1+mean), inverse-CDF draw); the tail
                beyond the timeout expires unanswered — the natural
                timeout-vs-straggler study.
    weighted  — coupled to the `latency_weight` sampling-propensity
                plane: the max-weight (nearest) peer answers in 0
                rounds, the min-weight peer in `latency_rounds`, linear
                in the weight in between.  Uniform weights give all-0 —
                bit-exact with the synchronous round.
    rtt       — topology-coupled: ``cfg.rtt_matrix[cq][cp]`` rounds for
                a draw from querier cluster cq to responder cluster cp
                (contiguous-block clusters, the clustered sampler's own
                partition) — per-(querier, responder) latency from a
                tiny static C x C gather, no O(N^2) plane.  A uniform
                matrix is trajectory-identical to "fixed".

    `n_global` / `row_offset` place this block's rows in the global id
    space (sharded drivers pass their shard offset; cluster membership
    derives from GLOBAL ids).  `key` is the round's SAMPLING key: the
    latency stream derives from it by an internal fold, so turning
    latency on never perturbs the peer / fault draws (the latency-0
    parity pin depends on this).
    """
    key = jax.random.fold_in(key, _LAT_FOLD)
    timeout = cfg.timeout_rounds()
    if cfg.latency_mode in ("none", "fixed"):
        # "none" reaches here only when a scheduled cut/spike turned the
        # engine on: latency 0 within each intact path.
        base = cfg.latency_rounds if cfg.latency_mode == "fixed" else 0
        return jnp.full(peers.shape, min(base, timeout), jnp.int32)
    if cfg.latency_mode == "rtt":
        matrix = jnp.asarray(cfg.rtt_matrix, jnp.int32)
        rows = peers.shape[0]
        qc = _cluster_of(jnp.arange(rows, dtype=jnp.int32)
                         + jnp.asarray(row_offset, jnp.int32),
                         cfg.n_clusters, n_global)
        pc = _cluster_of(peers, cfg.n_clusters, n_global)
        return jnp.clip(matrix[qc[:, None], pc], 0, timeout)
    if cfg.latency_mode == "geometric":
        if cfg.latency_rounds == 0:
            return jnp.zeros(peers.shape, jnp.int32)
        p = 1.0 / (1.0 + cfg.latency_rounds)
        u = jax.random.uniform(key, peers.shape)
        lat = jnp.floor(jnp.log1p(-u) / math.log1p(-p)).astype(jnp.int32)
        return jnp.clip(lat, 0, timeout)
    # weighted: lat = latency_rounds * (wmax - w[peer]) / (wmax - wmin).
    w = latency_weight[peers]
    wmax = latency_weight.max()
    wmin = latency_weight.min()
    scale = (wmax - w) / jnp.maximum(wmax - wmin, jnp.float32(1e-9))
    lat = jnp.round(cfg.latency_rounds * scale).astype(jnp.int32)
    return jnp.clip(lat, 0, timeout)


def _partition_split(cfg: AvalancheConfig, n_global: int,
                     frac: float) -> int:
    """Static node-index split point of a partition event.

    Snapped to the nearest INTERIOR cluster boundary when the topology
    is clustered: at least one cluster on each side (a 0- or
    n_clusters-cluster "split" is no partition at all, and clamping at
    node granularity would break the no-cluster-straddles-the-cut
    contract).  floor(x+0.5), not round(): banker's rounding would turn
    a 0.5 frac at odd cluster counts into an off-by-one split.
    """
    if cfg.n_clusters > 1:
        split_cluster = int(math.floor(frac * cfg.n_clusters + 0.5))
        split_cluster = max(1, min(split_cluster, cfg.n_clusters - 1))
        # First id of cluster `split_cluster` under cluster_of's
        # ``i * C // N`` partition: ceil(c*N/C).  ``c * (N // C)``
        # lands inside a cluster whenever C does not divide N.
        return -(-split_cluster * n_global // cfg.n_clusters)
    return max(1, min(int(math.floor(frac * n_global)), n_global - 1))


def partition_cut(
    cfg: AvalancheConfig,
    round_: jax.Array,
    row_offset,
    peers: jax.Array,
    n_global: int,
    fault_params: Optional[FaultParams] = None,
) -> Optional[jax.Array]:
    """Bool ``[rows, k]`` — draws severed by any active CUT event this
    round; None (statically) when the merged fault script
    (`cfg.cut_events()`: partitions + regional outages, with
    `partition_spec` as the one-event sugar) schedules none.

    Every event's window is jit-STATIC: `round_` is the only traced
    input, so each event compiles to one ``[rows, k]`` mask AND'd with a
    scalar round-range test — the cond structure of the round is
    untouched, and an empty script is statically absent (all archived
    hlo pins byte-identical).

      partition(start, end, frac)        — querier and peer on opposite
        sides of the static split ``_partition_split`` (cluster-aligned
        when `n_clusters` > 1);
      regional_outage(start, end, c)     — exactly one endpoint inside
        cluster c (contiguous-block clusters, the clustered sampler's
        own partition): traffic into or out of the region is severed,
        intra-region and outside traffic unaffected.

    STOCHASTIC partitions (`cfg.stochastic_cut_events()`) compose the
    same way from the REALIZED `fault_params` the init key drew
    (`draw_fault_params`): the window test compares `round_` against
    traced start/end scalars and the split index is the realized one,
    so the compiled structure is identical to a static event's — one
    mask per event — while each trial's realization differs.

    The mask `apply_faults` stamps with the timeout sentinel, exposed on
    its own so the round's telemetry can count fault-blocked queries
    from the same plane (XLA CSEs the shared computation).
    """
    events = cfg.cut_events()
    n_sto = len(cfg.stochastic_cut_events())
    n_reg = len(cfg.stochastic_region_events())
    if not events and not n_sto and not n_reg:
        return None
    rows = peers.shape[0]
    qids = (jnp.arange(rows, dtype=jnp.int32)
            + jnp.asarray(row_offset, jnp.int32))
    cut = jnp.zeros(peers.shape, jnp.bool_)
    for kind, start, end, param in events:
        active = (round_ >= start) & (round_ < end)
        if kind == "partition":
            split = _partition_split(cfg, n_global, param)
            qside = qids < split
            pside = peers < split
        else:  # regional_outage
            region = jnp.int32(param)
            qside = _cluster_of(qids, cfg.n_clusters, n_global) == region
            pside = _cluster_of(peers, cfg.n_clusters,
                                n_global) == region
        cut = cut | (active & (qside[:, None] != pside))
    if n_sto:
        if fault_params is None:
            raise ValueError(
                "stochastic_partition events need the realized "
                "FaultParams drawn at init (state.fault_params) — the "
                "caller must thread it through (every model round "
                "does)")
        for i in range(n_sto):
            active = ((round_ >= fault_params.cut_start[i])
                      & (round_ < fault_params.cut_end[i]))
            split = fault_params.cut_split[i]
            cut = cut | (active & ((qids < split)[:, None]
                                   != (peers < split)))
    if n_reg:
        # stochastic_regional_outage: the severed CLUSTER is realized
        # per sim (drawn from the event's [lo, hi] range) — the window
        # test and the region id are traced scalars, the mask structure
        # is the static regional_outage's.
        if fault_params is None:
            raise ValueError(
                "stochastic_regional_outage events need the realized "
                "FaultParams drawn at init (state.fault_params) — the "
                "caller must thread it through (every model round "
                "does)")
        qc = _cluster_of(qids, cfg.n_clusters, n_global)
        pc = _cluster_of(peers, cfg.n_clusters, n_global)
        for i in range(n_reg):
            active = ((round_ >= fault_params.region_start[i])
                      & (round_ < fault_params.region_end[i]))
            region = fault_params.region_cluster[i]
            cut = cut | (active & ((qc == region)[:, None]
                                   != (pc == region)))
    return cut


def apply_latency_spikes(
    lat: jax.Array,
    cfg: AvalancheConfig,
    round_: jax.Array,
    fault_params: Optional[FaultParams] = None,
) -> jax.Array:
    """Add every active latency_spike event's extra rounds to this
    round's ISSUE-time latency draws (entries already in flight keep
    their stamped latency — a spike delays queries issued during it).

    Stochastic spikes (`cfg.stochastic_spike_events()`) add their
    REALIZED extra from `fault_params` under the realized (traced)
    window test — same additive composition.

    Clipped back to ``[0, timeout_rounds()]``: a spiked latency reaching
    the timeout becomes the never-delivers sentinel, so a spike taller
    than the timeout headroom turns into an expiry storm — exactly what
    a production timeout does to a latency excursion.  Statically absent
    with no spike events.
    """
    events = cfg.spike_events()
    n_sto = len(cfg.stochastic_spike_events())
    if not events and not n_sto:
        return lat
    extra = jnp.int32(0)
    for _, start, end, rounds_ in events:
        active = (round_ >= start) & (round_ < end)
        extra = extra + jnp.where(active, jnp.int32(rounds_),
                                  jnp.int32(0))
    if n_sto:
        if fault_params is None:
            raise ValueError(
                "stochastic_spike events need the realized FaultParams "
                "drawn at init (state.fault_params) — the caller must "
                "thread it through (every model round does)")
        for i in range(n_sto):
            active = ((round_ >= fault_params.spike_start[i])
                      & (round_ < fault_params.spike_end[i]))
            extra = extra + jnp.where(active, fault_params.spike_extra[i],
                                      jnp.int32(0))
    return jnp.clip(lat + extra, 0, cfg.timeout_rounds())


def apply_faults(
    lat: jax.Array,
    cfg: AvalancheConfig,
    round_: jax.Array,
    row_offset,
    peers: jax.Array,
    n_global: int,
    fault_params: Optional[FaultParams] = None,
) -> jax.Array:
    """The fault-script engine's issue-time pass: latency spikes, then
    cut events (partitions / regional outages) — static events from the
    script, stochastic ones from the realized `fault_params` the init
    key drew (`draw_fault_params`; every model carries them as
    `state.fault_params`).

    A draw severed by an active cut never delivers — its latency becomes
    the timeout sentinel, so it EXPIRES unanswered at age
    `timeout_rounds()` (the host Processor's reap), including entries
    issued just before a heal: recovery trails every heal by the
    timeout.  With an empty merged script both passes are statically
    absent and `lat` flows through untouched (pins unchanged).
    """
    lat = apply_latency_spikes(lat, cfg, round_, fault_params)
    cut = partition_cut(cfg, round_, row_offset, peers, n_global,
                        fault_params)
    if cut is None:
        return lat
    return jnp.where(cut, jnp.int32(cfg.timeout_rounds()), lat)


# Back-compat spelling from PR 3, when the only schedulable fault was
# the single partition; same contract as `apply_faults`.
apply_partition = apply_faults


_BURST_FOLD = 0x0B57


def apply_churn_bursts(
    alive: jax.Array,
    cfg: AvalancheConfig,
    round_: jax.Array,
    key: jax.Array,
) -> jax.Array:
    """Apply every scheduled churn_burst event to the alive plane.

    At event round r, each row toggles dead<->alive with probability
    `frac` — a one-shot `churn_probability` impulse, same toggle
    semantics (a dead node revives with the same coin).  `key` is the
    round's CHURN key (already shard-folded on the sharded drivers); the
    burst stream folds in `_BURST_FOLD` plus the event index so bursts
    never perturb the steady-state churn draws, and multiple bursts stay
    independent.  Statically absent with no churn_burst events — the
    alive plane passes through untraced (pins unchanged).
    """
    events = cfg.churn_burst_events()
    if not events:
        return alive
    for i, (_, r, frac) in enumerate(events):
        k = jax.random.fold_in(jax.random.fold_in(key, _BURST_FOLD), i)
        toggle = jax.random.bernoulli(k, frac, alive.shape)
        alive = jnp.logical_xor(alive, toggle & (round_ == r))
    return alive


def enqueue(
    ring: InflightState,
    round_: jax.Array,
    peers: jax.Array,
    lat: jax.Array,
    responded: jax.Array,
    lie: jax.Array,
    polled: jax.Array,
) -> InflightState:
    """Write this round's queries into slot ``round_ % D``.

    `polled` is the round's bool update mask; when the ring stores its
    poll-mask plane bit-packed (coalesced engine) it is packed here —
    enqueue always runs where the plane's width is the LOCAL one (the
    host model, or a shard's block inside `shard_map`), so the plain
    single-block packing is the right layout in both settings.
    """
    d = ring.peers.shape[0]
    slot = jnp.mod(round_, d).astype(jnp.int32)
    if ring.polled.dtype == jnp.uint8 and polled.dtype != jnp.uint8:
        polled = pack_bool_plane(polled)

    def upd(plane, entry):
        return lax.dynamic_update_index_in_dim(plane, entry.astype(
            plane.dtype), slot, 0)

    return InflightState(
        peers=upd(ring.peers, peers),
        lat=upd(ring.lat, lat),
        responded=upd(ring.responded, responded),
        lie=upd(ring.lie, lie),
        polled=upd(ring.polled, polled),
    )


def _delivery_key(key: jax.Array, d: jax.Array) -> jax.Array:
    """Per-age adversary key: age 0 uses the round key VERBATIM (latency-0
    bit-parity with the synchronous round's equivocation coins), older
    ages fold the age in for an independent stream."""
    return lax.cond(d == 0, lambda: key,
                    lambda: jax.random.fold_in(key, d))


def _pack_bits(bits: jax.Array) -> jax.Array:
    """bool ``[rows, k]`` -> uint8 ``[rows]``, bit j = draw j."""
    k = bits.shape[1]
    shifts = jnp.arange(k, dtype=jnp.uint8)
    return (bits.astype(jnp.uint8) << shifts).sum(axis=1).astype(jnp.uint8)


def deliver_multi(
    ring: InflightState,
    records: vr.VoteRecordState,
    cfg: AvalancheConfig,
    packed_prefs: jax.Array,
    minority_t: jax.Array,
    key: jax.Array,
    round_: jax.Array,
    t: int,
    live_rows: Optional[jax.Array] = None,
    ctx: Optional[adversary.PolicyCtx] = None,
) -> Tuple[vr.VoteRecordState, jax.Array, jax.Array]:
    """One round's delivery+expiry pass for the multi-target models.

    Walks ring ages oldest-first (``timeout_rounds() .. 0``) in a
    `fori_loop` — compiled size is O(1) in the ring depth.  Per age:
    entries whose latency matches deliver (gather via the
    `cfg.fused_exchange` engine dispatch against `packed_prefs`, the
    PRE-ROUND preference plane — all of a round's responses observe the
    round-start state, the synchronous round's own convention); entries
    at the timeout age with the never-delivers latency expire unanswered.
    Both ingest through `register_packed_votes_present` with the stored
    issue-time poll mask, further masked by records that finalized while
    the query was in flight (the reference deletes finalized records, so
    late votes never reach them, `processor.go:114-116`) and — when
    `live_rows` (bool ``[rows]``, the round-start alive slice) is given —
    by queriers that churned DEAD while their query was in flight: a dead
    node's records stay frozen, the same invariant the synchronous
    round's ``polled & alive`` mask maintains.

    Returns ``(records, changed, votes_applied)`` — `changed` OR-reduced
    over ages, `votes_applied` the delivered non-neutral ingest count
    (same accounting as the synchronous round's telemetry).
    """
    timeout = cfg.timeout_rounds()
    depth = timeout + 1

    def body(i, carry):
        records, changed, votes_applied = carry
        d = jnp.int32(timeout) - i
        slot = jnp.mod(round_ - d + depth, depth)
        peers = lax.dynamic_index_in_dim(ring.peers, slot, 0, False)
        lat = lax.dynamic_index_in_dim(ring.lat, slot, 0, False)
        responded = lax.dynamic_index_in_dim(ring.responded, slot, 0, False)
        lie = lax.dynamic_index_in_dim(ring.lie, slot, 0, False)
        polled = lax.dynamic_index_in_dim(ring.polled, slot, 0, False)

        deliver = (lat == d[None, None]) & (d != timeout)
        expire = (lat >= timeout) & (d == timeout)
        consider = responded & deliver
        present = deliver | expire
        if cfg.skip_absent_votes:
            present = present & consider

        yes_pack, consider_pack = exchange.gather_vote_packs(
            packed_prefs, peers, consider, lie,
            _delivery_key(key, d), cfg, minority_t, t, ctx)
        present_pack = jnp.broadcast_to(
            _pack_bits(present)[:, None], consider_pack.shape)
        update_mask = polled & jnp.logical_not(
            vr.has_finalized(records.confidence, cfg))
        if live_rows is not None:
            update_mask = update_mask & live_rows[:, None]
        records, ch = vr.register_packed_votes_present(
            records, yes_pack, consider_pack, present_pack, cfg.k, cfg,
            update_mask=update_mask)
        changed = changed | ch
        votes_applied = votes_applied + (
            popcount8(consider_pack).astype(jnp.int32) * update_mask).sum()
        return records, changed, votes_applied

    changed0 = jnp.zeros(records.votes.shape, jnp.bool_)
    return lax.fori_loop(0, depth, body,
                         (records, changed0, jnp.int32(0)))


def deliver_1d(
    ring: InflightState,
    records: vr.VoteRecordState,
    cfg: AvalancheConfig,
    prefs: jax.Array,
    key: jax.Array,
    round_: jax.Array,
    live_rows: Optional[jax.Array] = None,
    ctx: Optional[adversary.PolicyCtx] = None,
) -> Tuple[vr.VoteRecordState, jax.Array]:
    """`deliver_multi` for single-decree Snowball (``[N]`` records).

    Same age walk, expiry semantics, and dead-querier freeze
    (`live_rows`); the response gather is a plain row gather of the
    pre-round ``[N]`` preference plane plus the 1-D adversary transform.
    Returns ``(records, changed)``.
    """
    timeout = cfg.timeout_rounds()
    depth = timeout + 1

    def body(i, carry):
        records, changed = carry
        d = jnp.int32(timeout) - i
        slot = jnp.mod(round_ - d + depth, depth)
        peers = lax.dynamic_index_in_dim(ring.peers, slot, 0, False)
        lat = lax.dynamic_index_in_dim(ring.lat, slot, 0, False)
        responded = lax.dynamic_index_in_dim(ring.responded, slot, 0, False)
        lie = lax.dynamic_index_in_dim(ring.lie, slot, 0, False)
        mask = lax.dynamic_index_in_dim(ring.polled, slot, 0, False)

        votes = adversary.apply_1d(_delivery_key(key, d), prefs[peers],
                                   lie, cfg, prefs, ctx)
        deliver = (lat == d[None, None]) & (d != timeout)
        expire = (lat >= timeout) & (d == timeout)
        consider = responded & deliver
        present = deliver | expire
        if cfg.skip_absent_votes:
            present = present & consider

        update_mask = mask & jnp.logical_not(
            vr.has_finalized(records.confidence, cfg))
        if live_rows is not None:
            update_mask = update_mask & live_rows
        records, ch = vr.register_packed_votes_present(
            records, _pack_bits(votes), _pack_bits(consider),
            _pack_bits(present), cfg.k, cfg, update_mask=update_mask)
        return records, changed | ch

    changed0 = jnp.zeros(records.votes.shape, jnp.bool_)
    return lax.fori_loop(0, depth, body, (records, changed0))


# ---------------------------------------------------------------------------
# walk_earlyout: the walk with a per-age lax.cond skip.
#
# Deliberately a TWIN of deliver_multi/deliver_1d rather than a flag on
# them: the walk's traced op order is pinned by the `flagship_async`
# hlo_pin hash, and hoisting its mask computation above the ring-plane
# slices (which the cond structure requires) would move that hash.


def deliver_multi_earlyout(
    ring: InflightState,
    records: vr.VoteRecordState,
    cfg: AvalancheConfig,
    packed_prefs: jax.Array,
    minority_t: jax.Array,
    key: jax.Array,
    round_: jax.Array,
    t: int,
    live_rows: Optional[jax.Array] = None,
    ctx: Optional[adversary.PolicyCtx] = None,
) -> Tuple[vr.VoteRecordState, jax.Array, jax.Array]:
    """`deliver_multi` with a per-age early-out (`cfg.inflight_engine =
    "walk_earlyout"`).

    Each age first reduces its slot's (no-T) latency planes to one
    "anything to do?" scalar; the gather, adversary transform and
    k-vote ingest run under a `lax.cond` only when some entry delivers
    or expires.  Identical results to the walk — an inert age is a
    no-op there too (present all-zero registers nothing) — but an inert
    age now costs a ``[rows, k]`` reduction instead of a full
    gather+ingest pass: the cheap win when latency << timeout leaves
    most ring ages empty-handed each round.
    """
    timeout = cfg.timeout_rounds()
    depth = timeout + 1

    def body(i, carry):
        d = jnp.int32(timeout) - i
        slot = jnp.mod(round_ - d + depth, depth)
        lat = lax.dynamic_index_in_dim(ring.lat, slot, 0, False)
        responded = lax.dynamic_index_in_dim(ring.responded, slot, 0, False)

        deliver = (lat == d[None, None]) & (d != timeout)
        expire = (lat >= timeout) & (d == timeout)
        consider = responded & deliver
        present = deliver | expire
        if cfg.skip_absent_votes:
            present = present & consider

        def run(carry):
            records, changed, votes_applied = carry
            peers = lax.dynamic_index_in_dim(ring.peers, slot, 0, False)
            lie = lax.dynamic_index_in_dim(ring.lie, slot, 0, False)
            polled = lax.dynamic_index_in_dim(ring.polled, slot, 0, False)
            yes_pack, consider_pack = exchange.gather_vote_packs(
                packed_prefs, peers, consider, lie,
                _delivery_key(key, d), cfg, minority_t, t, ctx)
            present_pack = jnp.broadcast_to(
                _pack_bits(present)[:, None], consider_pack.shape)
            update_mask = polled & jnp.logical_not(
                vr.has_finalized(records.confidence, cfg))
            if live_rows is not None:
                update_mask = update_mask & live_rows[:, None]
            records, ch = vr.register_packed_votes_present(
                records, yes_pack, consider_pack, present_pack, cfg.k,
                cfg, update_mask=update_mask)
            votes_applied = votes_applied + (
                popcount8(consider_pack).astype(jnp.int32)
                * update_mask).sum()
            return records, changed | ch, votes_applied

        return lax.cond(present.any(), run, lambda c: c, carry)

    changed0 = jnp.zeros(records.votes.shape, jnp.bool_)
    return lax.fori_loop(0, depth, body,
                         (records, changed0, jnp.int32(0)))


def deliver_1d_earlyout(
    ring: InflightState,
    records: vr.VoteRecordState,
    cfg: AvalancheConfig,
    prefs: jax.Array,
    key: jax.Array,
    round_: jax.Array,
    live_rows: Optional[jax.Array] = None,
    ctx: Optional[adversary.PolicyCtx] = None,
) -> Tuple[vr.VoteRecordState, jax.Array]:
    """`deliver_1d` with the per-age early-out (see
    `deliver_multi_earlyout`)."""
    timeout = cfg.timeout_rounds()
    depth = timeout + 1

    def body(i, carry):
        d = jnp.int32(timeout) - i
        slot = jnp.mod(round_ - d + depth, depth)
        lat = lax.dynamic_index_in_dim(ring.lat, slot, 0, False)
        responded = lax.dynamic_index_in_dim(ring.responded, slot, 0, False)

        deliver = (lat == d[None, None]) & (d != timeout)
        expire = (lat >= timeout) & (d == timeout)
        consider = responded & deliver
        present = deliver | expire
        if cfg.skip_absent_votes:
            present = present & consider

        def run(carry):
            records, changed = carry
            peers = lax.dynamic_index_in_dim(ring.peers, slot, 0, False)
            lie = lax.dynamic_index_in_dim(ring.lie, slot, 0, False)
            mask = lax.dynamic_index_in_dim(ring.polled, slot, 0, False)
            votes = adversary.apply_1d(_delivery_key(key, d), prefs[peers],
                                       lie, cfg, prefs, ctx)
            update_mask = mask & jnp.logical_not(
                vr.has_finalized(records.confidence, cfg))
            if live_rows is not None:
                update_mask = update_mask & live_rows
            records, ch = vr.register_packed_votes_present(
                records, _pack_bits(votes), _pack_bits(consider),
                _pack_bits(present), cfg.k, cfg, update_mask=update_mask)
            return records, changed | ch

        return lax.cond(present.any(), run, lambda c: c, carry)

    changed0 = jnp.zeros(records.votes.shape, jnp.bool_)
    return lax.fori_loop(0, depth, body, (records, changed0))


# ---------------------------------------------------------------------------
# coalesced: one-pass ring drain.


def _age_loop_bounds(cfg: AvalancheConfig, depth: int):
    """Static ``(lo, hi)`` bounds for the coalesced per-age drain loop.

    General case: the full ring, ``(0, depth)``.  Fixed latency
    (`_static_single_age`): a TRIP-2 window containing the one age that
    can ever register — depth-independent, but deliberately not trip-1:
    XLA's while-loop simplifier unrolls a single-iteration loop, which
    hoists the activity `lax.cond` to the scan body's top level where
    its operand copies (and its serial branch execution) cost ~3x the
    looped form on CPU (PERF_NOTES PR 4); the one extra inert
    iteration keeps the while intact for one scalar test + one
    pass-through copy.
    """
    single_age = _static_single_age(cfg)
    if single_age is None:
        return 0, depth
    hi = min(depth, (cfg.timeout_rounds() - single_age) + 2)
    return hi - 2, hi


def _static_single_age(cfg: AvalancheConfig):
    """The one ring age that can ever register under this config, or
    None when that is not statically known.

    With ``latency_mode="fixed"`` and no cut/spike events scheduled,
    every enqueued entry carries the SAME latency
    ``min(latency_rounds, timeout)``: if it is below the timeout, only
    that age ever delivers (and nothing ever expires — the stored
    latency never reaches the sentinel); if it IS the timeout sentinel,
    nothing ever delivers and only the expiry age registers.  Either
    way exactly one age needs processing, so the coalesced drain skips
    the per-age activity loop entirely — ring depth affects nothing but
    slot arithmetic, which is what makes the fixed-latency bench lane
    depth-independent (PERF_NOTES PR 4 depth sweep).  A UNIFORM
    cluster-pair RTT matrix is the same constant-latency invariant, so
    "rtt" qualifies too when every entry is equal.  Any scheduled cut
    or spike breaks the invariant (sentinel stamps / shifted windows),
    so a non-empty merged script falls back to the general bounds.

    This is an invariant of rings POPULATED UNDER the same config
    (`draw_latency` stamps the constant; every model does).  A
    hand-built ring with mixed latencies must pair with a non-fixed
    `latency_mode` — which is also the only way production reaches
    such a state (tests/test_inflight.py collision parity).
    """
    if cfg.cut_events() or cfg.spike_events() or cfg.stochastic_events():
        return None
    if cfg.adversary_policy in ("timing", "withhold_near_quorum"):
        # Both stamp PER-DRAW latencies at issue time (timeout - 1 for
        # timed lies, the sentinel for withheld draws), so a "fixed"
        # ring carries mixed latencies and more than one age registers.
        return None
    if cfg.latency_mode == "fixed":
        return min(cfg.latency_rounds, cfg.timeout_rounds())
    if cfg.latency_mode == "rtt":
        entries = {entry for row in cfg.rtt_matrix for entry in row}
        if len(entries) == 1:
            return min(entries.pop(), cfg.timeout_rounds())
    return None


class _RingAgeView(NamedTuple):
    """Whole-ring per-age planes, oldest-age-first (see
    `_ring_age_view`).  The ONE spelling of the mod-depth age
    arithmetic — the delivery engines consume `slots`/`consider`/
    `present`; `ring_telemetry` reads the raw `ages`/`lat`/`deliver`/
    `expire` planes so its counters can never desync from what the
    engines deliver."""

    slots: jax.Array     # int32 [D] — processing index -> ring slot
    consider: jax.Array  # bool [D, rows, k] — delivering AND responded
    present: jax.Array   # bool [D, rows, k] — window-shifting this round
    ages: jax.Array      # int32 [D] — age per processing index
    lat: jax.Array       # int32 [D, rows, k] — latencies, slot-gathered
    deliver: jax.Array   # bool [D, rows, k] — latency matches age (raw)
    expire: jax.Array    # bool [D, rows, k] — timeout reap (raw)


def _ring_age_view(ring: InflightState, cfg: AvalancheConfig,
                   round_: jax.Array) -> _RingAgeView:
    """Whole-ring deliverable/expiry masks, oldest-age-first.

    `slots` int32 ``[D]`` maps PROCESSING index i (age ``timeout - i``:
    i=0 is the expiring age, i=depth-1 the round's own enqueue) to its
    ring slot; the masks are bool ``[D, rows, k]`` — the same per-age
    masks the walk computes one `fori_loop` iteration at a time,
    materialized for the whole ring at once from the ring's (no-T)
    latency planes.
    """
    timeout = cfg.timeout_rounds()
    depth = timeout + 1
    ages = jnp.arange(timeout, -1, -1, dtype=jnp.int32)        # oldest first
    slots = jnp.mod(round_ - ages, depth).astype(jnp.int32)
    lat = jnp.take(ring.lat, slots, axis=0)
    responded = jnp.take(ring.responded, slots, axis=0)
    a3 = ages[:, None, None]
    deliver = (lat == a3) & (a3 != jnp.int32(timeout))
    expire = (lat >= timeout) & (a3 == jnp.int32(timeout))
    consider = responded & deliver
    present = deliver | expire
    if cfg.skip_absent_votes:
        present = present & consider
    return _RingAgeView(slots=slots, consider=consider, present=present,
                        ages=ages, lat=lat, deliver=deliver, expire=expire)


def _vote_transition(votes, consider, confidence, yes_cnt, cons_cnt,
                     in_yes_raw, in_cons, pres, cfg: AvalancheConfig):
    """One present-gated window shift + confidence transition.

    The `_apply_vote_bits` state machine with the per-slot popcounts
    replaced by the incremental yes/consider counters of the
    `register_packed_votes` hot loop (the counters ride the same
    `pres` selects as the windows, so they always count the SELECTED
    windows' bits).  `in_yes_raw` / `pres` are bool arrays of the state
    shape (or broadcastable); `in_cons` likewise.  Returns the updated
    ``(votes, consider, confidence, yes_cnt, cons_cnt, changed)``.
    """
    one = jnp.uint8(1)
    top_bit = cfg.window - 1
    threshold = jnp.uint8(cfg.quorum - 1)
    iy_raw = in_yes_raw.astype(jnp.uint8)
    ic = in_cons.astype(jnp.uint8)
    in_yes = iy_raw & ic                        # counted iff considered

    evict_yes = ((votes & consider) >> top_bit) & one
    evict_cons = (consider >> top_bit) & one
    ny = yes_cnt + in_yes - evict_yes
    nc = cons_cnt + ic - evict_cons

    nv = (votes << 1) | iy_raw
    ncs = (consider << 1) | ic
    if cfg.window != 8:                         # uint8 shifts self-truncate
        window_mask = jnp.uint8((1 << cfg.window) - 1)
        nv = nv & window_mask
        ncs = ncs & window_mask

    yes = ny > threshold
    no = (nc - ny) > threshold
    conclusive = yes | no
    accepted = (confidence & 1) == 1
    agree = accepted == yes
    saturated = (confidence >> 1) >= jnp.uint16(0x7FFF)
    conf_bumped = jnp.where(saturated, confidence,
                            confidence + jnp.uint16(2))
    conf_new = jnp.where(conclusive,
                         jnp.where(agree, conf_bumped,
                                   yes.astype(jnp.uint16)),
                         confidence)
    finalized_now = ((conf_bumped >> 1) == cfg.finalization_score) & agree
    ch = conclusive & (jnp.logical_not(agree) | finalized_now) & pres

    votes = jnp.where(pres, nv, votes)
    consider = jnp.where(pres, ncs, consider)
    confidence = jnp.where(pres, conf_new, confidence)
    yes_cnt = jnp.where(pres, ny, yes_cnt)
    cons_cnt = jnp.where(pres, nc, cons_cnt)
    return votes, consider, confidence, yes_cnt, cons_cnt, ch


def deliver_multi_coalesced(
    ring: InflightState,
    records: vr.VoteRecordState,
    cfg: AvalancheConfig,
    packed_prefs: jax.Array,
    minority_t: jax.Array,
    key: jax.Array,
    round_: jax.Array,
    t: int,
    live_rows: Optional[jax.Array] = None,
    ctx: Optional[adversary.PolicyCtx] = None,
) -> Tuple[vr.VoteRecordState, jax.Array, jax.Array]:
    """One-pass ring drain for the multi-target models
    (`cfg.inflight_engine = "coalesced"`); same contract and identical
    bits as `deliver_multi` on every config axis (tests/test_inflight).

    The walk's runtime tracks ring DEPTH: every age pays its gather,
    its adversary transform and its k-vote ingest whether or not its
    slot has anything to deliver, so a deeper timeout at the same
    latency costs proportionally more.  Here the drain's per-round cost
    is proportional to DELIVERIES:

      * the deliverable/expiry masks come from `_ring_age_view` for the
        entire ``[D, rows, k]`` ring at once — no T axis involved, so
        the whole-ring mask pass is noise at any depth;
      * one static-bound `fori_loop` walks the ages oldest-first, each
        gated by its PRECOMPUTED "anything present" flag (a `lax.cond`
        whose body lowers exactly once): an inert age costs one scalar
        test — no ring-plane reads, no gather, no adversary coins, no
        window compute.  Under fixed latency exactly one age delivers
        per round, so the drain does one age's work at any ring depth;
        under geometric latency every age stays busy and the drain
        degrades to the walk's cost, never below it.

    Multi-age collisions on a draw slot land in the same sequence the
    walk applies them (active ages run oldest-first), the
    finalized-mid-flight / dead-querier / poll-mask gates fold into the
    per-slot present bits, and confidence is re-read at every age
    boundary exactly where the walk's per-age `update_mask` samples it.
    The per-slot transition is the incremental-counter form of the
    `register_packed_votes` hot loop (`_vote_transition`), not the
    two-popcount `_apply_vote_bits`, and the ring's poll-mask plane is
    read bit-packed (8x less traffic than the walk's bool plane).
    Compiled size is O(k), like the walk.
    """
    k = cfg.k
    view = _ring_age_view(ring, cfg, round_)
    slots, consider, present = view.slots, view.consider, view.present
    any_present = present.any(axis=(1, 2))               # [D] flags
    timeout = jnp.int32(cfg.timeout_rounds())

    def body(ai, carry):
        records, changed, votes_applied = carry
        d = timeout - ai                    # oldest age first
        slot = slots[ai]
        peers = lax.dynamic_index_in_dim(ring.peers, slot, 0, False)
        lie = lax.dynamic_index_in_dim(ring.lie, slot, 0, False)
        polled = lax.dynamic_index_in_dim(ring.polled, slot, 0, False)
        consider_i = lax.dynamic_index_in_dim(consider, ai, 0, False)
        present_i = lax.dynamic_index_in_dim(present, ai, 0, False)
        # Per-age update gate — confidence is re-read HERE, after the
        # older ages' slots applied, exactly like the walk's per-age
        # update_mask (finalized-mid-flight records freeze mid-drain).
        upd = unpack_bool_plane(polled, t) \
            & jnp.logical_not(vr.has_finalized(records.confidence, cfg))
        if live_rows is not None:
            upd = upd & live_rows[:, None]
        rows = peers.shape[0]
        cube = packed_prefs[peers.reshape(rows * k)].reshape(
            rows, k, packed_prefs.shape[-1])
        votes_adv = adversary.apply_draw_planes(
            _delivery_key(key, d), unpack_bool_plane(cube, t), lie, cfg,
            minority_t, ctx)                              # [rows, k, T]
        votes_applied = votes_applied + jnp.where(
            upd, popcount8(_pack_bits(consider_i))[:, None]
            .astype(jnp.int32), 0).sum()
        votes_w, cons_w, confidence = records
        yes_cnt = popcount8(votes_w & cons_w)
        cons_cnt = popcount8(cons_w)
        for j in range(k):                  # unrolled: k is static
            pres = present_i[:, j][:, None] & upd
            (votes_w, cons_w, confidence, yes_cnt, cons_cnt,
             ch) = _vote_transition(
                votes_w, cons_w, confidence, yes_cnt, cons_cnt,
                votes_adv[:, j, :], consider_i[:, j][:, None], pres, cfg)
            changed = changed | ch
        return (vr.VoteRecordState(votes_w, cons_w, confidence),
                changed, votes_applied)

    carry = (records,
             jnp.zeros(records.votes.shape, jnp.bool_),    # changed
             jnp.int32(0))                                 # votes applied
    # STATIC-bound fori, each age gated by ITS OWN precomputed activity
    # flag: the body — and with it the conditional's record-plane copy
    # set — lowers exactly once, and an inert age costs one scalar
    # test plus the skip branch's record-plane pass-through copy.
    # Under fixed latency the bounds tighten STATICALLY to the single
    # age that can ever register (`_static_single_age`), which is what
    # makes the bench lane depth-independent.  The loop+cond structure
    # itself is load-bearing on three counts (PERF_NOTES PR 4): a
    # traced-bound `fori_loop(0, n_active, ...)` over argsort-compacted
    # active ages makes copy-insertion clone the aliased ring/record
    # buffers every round under the donated flagship scan; `argsort`
    # inside `shard_map` miscompiles on jax 0.4.37 (returns the
    # identity permutation on shards whose active set has gaps; pinned
    # by the sharded geometric parity test); and hoisting the cond out
    # of the while body — one `lax.cond` per age unrolled at the scan
    # body's top level, or the single-age cond called bare — re-inserts
    # the conditional's operand copies once per occurrence per round.
    lo, hi = _age_loop_bounds(cfg, int(ring.peers.shape[0]))
    return lax.fori_loop(
        lo, hi,
        lambda n, c: lax.cond(any_present[n],
                              functools.partial(body, n), lambda cc: cc,
                              c),
        carry)


def deliver_1d_coalesced(
    ring: InflightState,
    records: vr.VoteRecordState,
    cfg: AvalancheConfig,
    prefs: jax.Array,
    key: jax.Array,
    round_: jax.Array,
    live_rows: Optional[jax.Array] = None,
    ctx: Optional[adversary.PolicyCtx] = None,
) -> Tuple[vr.VoteRecordState, jax.Array]:
    """`deliver_multi_coalesced` for single-decree Snowball (``[N]``
    records): whole-ring masks, then one static-bound `fori_loop` whose
    per-age activity cond drains exactly the ages with something to
    deliver."""
    k = cfg.k
    view = _ring_age_view(ring, cfg, round_)
    slots, consider, present = view.slots, view.consider, view.present
    any_present = present.any(axis=(1, 2))               # [D] flags
    timeout = jnp.int32(cfg.timeout_rounds())

    def body(ai, carry):
        records, changed = carry
        votes_w, cons_w, confidence = records
        d = timeout - ai
        slot = slots[ai]
        peers = lax.dynamic_index_in_dim(ring.peers, slot, 0, False)
        lie = lax.dynamic_index_in_dim(ring.lie, slot, 0, False)
        mask = lax.dynamic_index_in_dim(ring.polled, slot, 0, False)
        consider_i = lax.dynamic_index_in_dim(consider, ai, 0, False)
        present_i = lax.dynamic_index_in_dim(present, ai, 0, False)
        upd = mask & jnp.logical_not(vr.has_finalized(confidence, cfg))
        if live_rows is not None:
            upd = upd & live_rows
        votes_adv = adversary.apply_1d(_delivery_key(key, d),
                                       prefs[peers], lie, cfg, prefs, ctx)
        yes_cnt = popcount8(votes_w & cons_w)
        cons_cnt = popcount8(cons_w)
        for j in range(k):                  # unrolled: k is static
            pres = present_i[:, j] & upd
            (votes_w, cons_w, confidence, yes_cnt, cons_cnt,
             ch) = _vote_transition(
                votes_w, cons_w, confidence, yes_cnt, cons_cnt,
                votes_adv[:, j], consider_i[:, j], pres, cfg)
            changed = changed | ch
        return (vr.VoteRecordState(votes_w, cons_w, confidence), changed)

    carry = (records, jnp.zeros(records.votes.shape, jnp.bool_))
    # Static-bound fori + per-age activity cond, with fixed-latency
    # single-age bounds: see deliver_multi_coalesced.
    lo, hi = _age_loop_bounds(cfg, int(ring.peers.shape[0]))
    return lax.fori_loop(
        lo, hi,
        lambda n, c: lax.cond(any_present[n],
                              functools.partial(body, n), lambda cc: cc,
                              c),
        carry)


# ---------------------------------------------------------------------------
# Engine dispatch — the single entry points every round implementation
# calls (`models/avalanche`, `models/dag`, `models/snowball`,
# `parallel/sharded`, `parallel/sharded_dag`; the streaming/backlog
# schedulers inherit through those rounds).


def deliver_multi_engine(
    ring: InflightState,
    records: vr.VoteRecordState,
    cfg: AvalancheConfig,
    packed_prefs: jax.Array,
    minority_t: jax.Array,
    key: jax.Array,
    round_: jax.Array,
    t: int,
    live_rows: Optional[jax.Array] = None,
    ctx: Optional[adversary.PolicyCtx] = None,
) -> Tuple[vr.VoteRecordState, jax.Array, jax.Array]:
    """`cfg.inflight_engine` dispatch for the multi-target delivery pass;
    identical bits whichever engine runs (tests/test_inflight)."""
    engine = {"walk": deliver_multi,
              "walk_earlyout": deliver_multi_earlyout,
              "coalesced": deliver_multi_coalesced}[cfg.inflight_engine]
    return engine(ring, records, cfg, packed_prefs, minority_t, key,
                  round_, t, live_rows=live_rows, ctx=ctx)


def deliver_1d_engine(
    ring: InflightState,
    records: vr.VoteRecordState,
    cfg: AvalancheConfig,
    prefs: jax.Array,
    key: jax.Array,
    round_: jax.Array,
    live_rows: Optional[jax.Array] = None,
    ctx: Optional[adversary.PolicyCtx] = None,
) -> Tuple[vr.VoteRecordState, jax.Array]:
    """`cfg.inflight_engine` dispatch for the single-decree delivery
    pass (Snowball)."""
    engine = {"walk": deliver_1d,
              "walk_earlyout": deliver_1d_earlyout,
              "coalesced": deliver_1d_coalesced}[cfg.inflight_engine]
    return engine(ring, records, cfg, prefs, key, round_,
                  live_rows=live_rows, ctx=ctx)


class RingTelemetry(NamedTuple):
    """Per-round ring counters (int32 scalars) — (querier, draw) ENTRY
    granularity, unlike the vote counters' (querier, draw, tx) votes."""

    deliveries: jax.Array  # responses delivered (responded & on-time)
    expiries: jax.Array    # entries expired unanswered at the timeout age
    occupancy: jax.Array   # entries still in flight after this round


def ring_telemetry(
    ring: Optional[InflightState],
    cfg: AvalancheConfig,
    round_: jax.Array,
) -> RingTelemetry:
    """Ring activity counters for the round that just drained slot ages.

    Everything comes from the ring's no-T latency planes — the same
    ``[D, rows, k]`` masks every delivery engine derives per age
    (`_ring_age_view`), reduced to three scalars; no gathers, no record
    reads, engine- and layout-independent (the bit-packed coalesced ring
    carries identical `lat`/`responded` planes).  Ages the ring has not
    been through yet (``age > round_``: the init-time pre-expired slots
    of the first ``D - 1`` rounds) are masked out, so an empty ring
    reads 0 everywhere.

      deliveries — entries whose latency matched their age this round
                   AND whose issue-time `responded` bit is set (a
                   non-responding draw leaves the ring silently at its
                   delivery age: it delivers absence, not a vote);
      expiries   — entries reaching the timeout age with the
                   never-delivers sentinel (partition cuts, latency
                   tails) — the host Processor's reap count;
      occupancy  — entries below the timeout age whose latency is still
                   ahead of them: the ring's fill AFTER this round's
                   deliveries left it.

    On a sharded driver the ring holds this shard's node rows: psum the
    counters over the NODES axis only (the planes are replicated across
    tx shards), which reproduces the dense counters bit-for-bit.
    None ring (engine off) returns static zeros.
    """
    zero = jnp.int32(0)
    if ring is None:
        return RingTelemetry(zero, zero, zero)
    # The engines' own age view (`_ring_age_view` — the one spelling of
    # the mod-depth arithmetic); telemetry adds only the `issued` gate
    # (slots the ring has not been through yet read as empty) and the
    # still-pending mask.
    v = _ring_age_view(ring, cfg, round_)
    timeout = jnp.int32(cfg.timeout_rounds())
    a3 = v.ages[:, None, None]
    issued = (v.ages <= round_)[:, None, None]        # slot written yet?
    pending = (v.lat > a3) & (a3 != timeout) & issued
    return RingTelemetry(
        deliveries=(v.consider & issued).sum().astype(jnp.int32),
        expiries=(v.expire & issued).sum().astype(jnp.int32),
        occupancy=pending.sum().astype(jnp.int32),
    )


def clear_columns(ring: Optional[InflightState],
                  cols: jax.Array) -> Optional[InflightState]:
    """Drop pending updates for window columns being retired/refilled.

    The streaming schedulers (`models/backlog`, `models/streaming_dag`
    and their sharded twins) reuse window columns for NEW txs; a response
    still in flight for the old occupant must not land on its
    replacement, so every ring slot's stored poll mask drops the refilled
    columns.  `cols` is bool ``[W]`` (True = column re-assigned); None
    ring (engine off) passes through.  A bit-packed poll-mask plane
    (coalesced engine) clears the same columns as packed bits — pad
    bits of ``~packed(cols)`` are 1, which keeps the plane's (already
    zero) pad bits untouched.
    """
    if ring is None:
        return None
    if ring.polled.dtype == jnp.uint8:
        keep = jnp.bitwise_not(pack_bool_plane(cols[None, :])[0])
        return ring._replace(polled=ring.polled & keep[None, None, :])
    return ring._replace(
        polled=ring.polled & jnp.logical_not(cols)[None, None, :])


def clear_rows(ring: Optional[InflightState],
               rows: jax.Array,
               peer_rows: Optional[jax.Array] = None
               ) -> Optional[InflightState]:
    """Drop pending updates for window ROWS being rotated out.

    The node-axis streaming scheduler (`models/node_stream` and its
    sharded twin) reuses window rows for NEW registry nodes; a response
    still in flight for the departed node must not land on — or be
    answered on behalf of — its replacement:

      * `rows` (bool ``[rows_local]``, True = row re-assigned) masks
        the departed rows as QUERIERS — their stored poll masks drop,
        so nothing ever registers on the replacement's records;
      * `peer_rows` (bool ``[W]`` over GLOBAL window row ids — the
        FULL swap mask on a sharded driver, where `rows` is the local
        block) masks them as polled PEERS — in-flight entries whose
        stored peer departed lose their `responded` bit, so delivery
        gathers never attribute the REPLACEMENT's preference to the
        departed node (the entry delivers absence, exactly like a peer
        that churned dead).  Defaults to `rows` (the dense case, where
        local == global).

    None ring (engine off) passes through.  Row masking is
    layout-independent (the poll-mask plane's row axis is never
    packed), so the packed coalesced ring takes the same `where`.
    """
    if ring is None:
        return None
    keep = jnp.logical_not(rows)
    polled_keep = (keep[None, :, None].astype(ring.polled.dtype)
                   if ring.polled.ndim == 3 else keep[None, :])
    peer_gone = (rows if peer_rows is None else peer_rows)[ring.peers]
    return ring._replace(
        polled=ring.polled * polled_keep if ring.polled.dtype == jnp.uint8
        else ring.polled & polled_keep,
        responded=(ring.responded & keep[None, :, None]
                   & jnp.logical_not(peer_gone)),
    )
