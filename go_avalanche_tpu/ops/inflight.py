"""The in-flight query engine: response latency, timeout expiry, and
partition faults for the batched simulators.

The reference Processor is fundamentally ASYNCHRONOUS: `event_loop` records
an outstanding query per poll (`processor.go:235-243`), responses arrive
whenever the network delivers them, and requests older than
`request_timeout_s` are reaped unanswered (`processor.go:61-122`,
`response.go:49-51` — honored today by the host twin `processor.py` only).
Every batched model, by contrast, resolved its k polls instantaneously
within the issuing round, so `cfg.request_timeout_s` was dead config on
the scale path and the only network fault was a memoryless drop.  Liveness
under message delay is qualitatively different from the synchronous ideal
(arXiv:2409.02217 quantifies Snowball liveness under partial synchrony;
TangleSim, arXiv:2305.01232, treats network latency as a first-class
simulation axis) — this module gives the `[N, T]` models that axis.

Mechanics — everything fixed-shape, `lax.scan`/`while_loop`-compatible,
no host round-trips:

  * each round's k polls per node are ENQUEUED into a depth-
    ``timeout_rounds() + 1`` ring buffer of pending-query planes carried
    in the sim state (`InflightState`), stamped with a per-(querier,
    draw) latency in rounds drawn by `draw_latency`
    (`cfg.latency_mode`: fixed / geometric / coupled to the
    `latency_weight` plane);
  * the DELIVERY pass (`deliver_multi` / `deliver_1d`) walks the ring
    oldest-age-first each round: an entry whose latency equals its age
    gathers the responder's CURRENT preference (responses reflect
    responder state at answer time; the query/transmission leg is
    instantaneous, which keeps gossip-on-poll at issue time) and ingests
    through the three-plane kernel
    (`voterecord.register_packed_votes_present`);
  * entries still undelivered at age `cfg.timeout_rounds()` EXPIRE
    UNANSWERED — exactly the host Processor's reaping
    (`processor.py:262-269`): under `cfg.skip_absent_votes` they
    register nothing (reference-host semantics, an expired response
    never reaches RegisterVotes), otherwise they shift the window as a
    delivered neutral, the same absence semantics drops get;
  * a partition fault (`cfg.partition_spec`) marks cross-cut draws
    undeliverable at ISSUE time — those queries time out rather than
    silently vanishing, so a healed partition shows the timeout tail,
    not an instant recovery.

Latency-0 (`latency_mode="fixed"`, `latency_rounds=0`) is bit-exact with
the synchronous round on every model and config axis
(tests/test_inflight.py golden parity): the just-enqueued entry delivers
in the same round, reading the same pre-round preference plane with the
same PRNG keys.  With `cfg.async_queries()` False the engine is
statically absent (state leaf None, zero trace impact — the flagship
`hlo_pin` hash is unchanged).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from go_avalanche_tpu.config import AvalancheConfig
from go_avalanche_tpu.ops import adversary, exchange, voterecord as vr
from go_avalanche_tpu.ops.bitops import popcount8

# fold_in constant deriving the latency stream from the round's sampling
# key: the latency draw must not perturb any existing stream (latency-0
# trajectories are pinned bit-exact against the synchronous round).
_LAT_FOLD = 0x1A7E


class InflightState(NamedTuple):
    """Ring buffer of pending queries; a pytree of ``[D, rows, ...]``
    planes (D = ``cfg.timeout_rounds() + 1``; rows = N, or n_local on a
    shard).  Slot ``r % D`` holds the queries ISSUED in round r; an
    entry's age in round ``r'`` is ``r' - r``, and the slot is
    overwritten exactly one round after its entries expire.

    `polled` is the issue-time update mask: bool ``[D, rows, T]`` for
    the multi-target models (unpacked on purpose — a bit-packed plane
    cannot shard over the txs axis at byte granularity when the
    per-shard width is not a multiple of 8; packing it per shard is a
    ROADMAP item for the hardware window), bool ``[D, rows]`` for
    single-decree Snowball.  `lat` is clipped to ``[0,
    timeout_rounds()]``; the top value is the NEVER-delivers sentinel
    (expires unanswered).
    """

    peers: jax.Array      # int32 [D, rows, k] — global peer ids
    lat: jax.Array        # int32 [D, rows, k] — delivery age; == timeout
                          #   sentinel means "expires unanswered"
    responded: jax.Array  # bool [D, rows, k] — issue-time alive/drop/self
    lie: jax.Array        # bool [D, rows, k] — issue-time adversary mask
    polled: jax.Array     # bool [D, rows, T], or bool [D, rows]
                          #   (snowball)


def enabled(cfg: AvalancheConfig) -> bool:
    """Static: is the in-flight engine on for this config?"""
    return cfg.async_queries()


def ring_depth(cfg: AvalancheConfig) -> int:
    """Slots in the ring: ages ``0 .. timeout_rounds()`` inclusive."""
    return cfg.timeout_rounds() + 1


def init_ring(cfg: AvalancheConfig, rows: int,
              t: Optional[int] = None) -> InflightState:
    """Empty ring: every slot pre-expired (lat = sentinel) with an
    all-zero update mask, so untouched slots never register anything."""
    d = ring_depth(cfg)
    k = cfg.k
    if t is None:            # single-decree: per-node bool mask
        polled = jnp.zeros((d, rows), jnp.bool_)
    else:                    # multi-target: per-(node, tx) bool mask
        polled = jnp.zeros((d, rows, t), jnp.bool_)
    return InflightState(
        peers=jnp.zeros((d, rows, k), jnp.int32),
        lat=jnp.full((d, rows, k), cfg.timeout_rounds(), jnp.int32),
        responded=jnp.zeros((d, rows, k), jnp.bool_),
        lie=jnp.zeros((d, rows, k), jnp.bool_),
        polled=polled,
    )


def draw_latency(
    key: jax.Array,
    cfg: AvalancheConfig,
    peers: jax.Array,
    latency_weight: jax.Array,
) -> jax.Array:
    """Per-(querier, draw) response latency in rounds; int32 ``[rows, k]``
    clipped to ``[0, timeout_rounds()]`` (the top value never delivers).

    fixed     — every draw takes `cfg.latency_rounds`.
    geometric — iid Geometric on {0, 1, ...} with mean `latency_rounds`
                (success prob p = 1/(1+mean), inverse-CDF draw); the tail
                beyond the timeout expires unanswered — the natural
                timeout-vs-straggler study.
    weighted  — coupled to the `latency_weight` sampling-propensity
                plane: the max-weight (nearest) peer answers in 0
                rounds, the min-weight peer in `latency_rounds`, linear
                in the weight in between.  Uniform weights give all-0 —
                bit-exact with the synchronous round.

    `key` is the round's SAMPLING key: the latency stream derives from it
    by an internal fold, so turning latency on never perturbs the peer /
    fault draws (the latency-0 parity pin depends on this).
    """
    key = jax.random.fold_in(key, _LAT_FOLD)
    timeout = cfg.timeout_rounds()
    if cfg.latency_mode in ("none", "fixed"):
        # "none" reaches here only when partition_spec turned the engine
        # on: latency 0 within each side of the cut.
        base = cfg.latency_rounds if cfg.latency_mode == "fixed" else 0
        return jnp.full(peers.shape, min(base, timeout), jnp.int32)
    if cfg.latency_mode == "geometric":
        if cfg.latency_rounds == 0:
            return jnp.zeros(peers.shape, jnp.int32)
        p = 1.0 / (1.0 + cfg.latency_rounds)
        u = jax.random.uniform(key, peers.shape)
        lat = jnp.floor(jnp.log1p(-u) / math.log1p(-p)).astype(jnp.int32)
        return jnp.clip(lat, 0, timeout)
    # weighted: lat = latency_rounds * (wmax - w[peer]) / (wmax - wmin).
    w = latency_weight[peers]
    wmax = latency_weight.max()
    wmin = latency_weight.min()
    scale = (wmax - w) / jnp.maximum(wmax - wmin, jnp.float32(1e-9))
    lat = jnp.round(cfg.latency_rounds * scale).astype(jnp.int32)
    return jnp.clip(lat, 0, timeout)


def apply_partition(
    lat: jax.Array,
    cfg: AvalancheConfig,
    round_: jax.Array,
    row_offset,
    peers: jax.Array,
    n_global: int,
) -> jax.Array:
    """Mark cross-partition draws undeliverable while the cut is active.

    During rounds ``[start, end)`` of `cfg.partition_spec`, a query whose
    querier and sampled peer sit on opposite sides of the split never
    delivers — its latency becomes the timeout sentinel, so it EXPIRES
    unanswered at age `timeout_rounds()` (the host Processor's reap),
    including entries issued just before the heal.  The split point is
    ``floor(split_frac * N)``, snapped to a cluster boundary when
    `cfg.n_clusters > 1` (contiguous-block clusters, `ops/sampling.py`).
    """
    if cfg.partition_spec is None:
        return lat
    start, end, frac = cfg.partition_spec
    if cfg.n_clusters > 1:
        # Snap to the nearest INTERIOR cluster boundary: at least one
        # cluster on each side (a 0- or n_clusters-cluster "split" is no
        # partition at all, and clamping at node granularity would break
        # the no-cluster-straddles-the-cut contract).  floor(x+0.5), not
        # round(): banker's rounding would turn a 0.5 frac at odd
        # cluster counts into an off-by-one split.
        csize = n_global // cfg.n_clusters
        split_cluster = int(math.floor(frac * cfg.n_clusters + 0.5))
        split_cluster = max(1, min(split_cluster, cfg.n_clusters - 1))
        split = split_cluster * csize
    else:
        split = max(1, min(int(math.floor(frac * n_global)), n_global - 1))
    rows = peers.shape[0]
    active = (round_ >= start) & (round_ < end)
    qside = (jnp.arange(rows, dtype=jnp.int32)
             + jnp.asarray(row_offset, jnp.int32)) < split
    pside = peers < split
    cut = active & (qside[:, None] != pside)
    return jnp.where(cut, jnp.int32(cfg.timeout_rounds()), lat)


def enqueue(
    ring: InflightState,
    round_: jax.Array,
    peers: jax.Array,
    lat: jax.Array,
    responded: jax.Array,
    lie: jax.Array,
    polled: jax.Array,
) -> InflightState:
    """Write this round's queries into slot ``round_ % D``."""
    d = ring.peers.shape[0]
    slot = jnp.mod(round_, d).astype(jnp.int32)

    def upd(plane, entry):
        return lax.dynamic_update_index_in_dim(plane, entry.astype(
            plane.dtype), slot, 0)

    return InflightState(
        peers=upd(ring.peers, peers),
        lat=upd(ring.lat, lat),
        responded=upd(ring.responded, responded),
        lie=upd(ring.lie, lie),
        polled=upd(ring.polled, polled),
    )


def _delivery_key(key: jax.Array, d: jax.Array) -> jax.Array:
    """Per-age adversary key: age 0 uses the round key VERBATIM (latency-0
    bit-parity with the synchronous round's equivocation coins), older
    ages fold the age in for an independent stream."""
    return lax.cond(d == 0, lambda: key,
                    lambda: jax.random.fold_in(key, d))


def _pack_bits(bits: jax.Array) -> jax.Array:
    """bool ``[rows, k]`` -> uint8 ``[rows]``, bit j = draw j."""
    k = bits.shape[1]
    shifts = jnp.arange(k, dtype=jnp.uint8)
    return (bits.astype(jnp.uint8) << shifts).sum(axis=1).astype(jnp.uint8)


def deliver_multi(
    ring: InflightState,
    records: vr.VoteRecordState,
    cfg: AvalancheConfig,
    packed_prefs: jax.Array,
    minority_t: jax.Array,
    key: jax.Array,
    round_: jax.Array,
    t: int,
    live_rows: Optional[jax.Array] = None,
) -> Tuple[vr.VoteRecordState, jax.Array, jax.Array]:
    """One round's delivery+expiry pass for the multi-target models.

    Walks ring ages oldest-first (``timeout_rounds() .. 0``) in a
    `fori_loop` — compiled size is O(1) in the ring depth.  Per age:
    entries whose latency matches deliver (gather via the
    `cfg.fused_exchange` engine dispatch against `packed_prefs`, the
    PRE-ROUND preference plane — all of a round's responses observe the
    round-start state, the synchronous round's own convention); entries
    at the timeout age with the never-delivers latency expire unanswered.
    Both ingest through `register_packed_votes_present` with the stored
    issue-time poll mask, further masked by records that finalized while
    the query was in flight (the reference deletes finalized records, so
    late votes never reach them, `processor.go:114-116`) and — when
    `live_rows` (bool ``[rows]``, the round-start alive slice) is given —
    by queriers that churned DEAD while their query was in flight: a dead
    node's records stay frozen, the same invariant the synchronous
    round's ``polled & alive`` mask maintains.

    Returns ``(records, changed, votes_applied)`` — `changed` OR-reduced
    over ages, `votes_applied` the delivered non-neutral ingest count
    (same accounting as the synchronous round's telemetry).
    """
    timeout = cfg.timeout_rounds()
    depth = timeout + 1

    def body(i, carry):
        records, changed, votes_applied = carry
        d = jnp.int32(timeout) - i
        slot = jnp.mod(round_ - d + depth, depth)
        peers = lax.dynamic_index_in_dim(ring.peers, slot, 0, False)
        lat = lax.dynamic_index_in_dim(ring.lat, slot, 0, False)
        responded = lax.dynamic_index_in_dim(ring.responded, slot, 0, False)
        lie = lax.dynamic_index_in_dim(ring.lie, slot, 0, False)
        polled = lax.dynamic_index_in_dim(ring.polled, slot, 0, False)

        deliver = (lat == d[None, None]) & (d != timeout)
        expire = (lat >= timeout) & (d == timeout)
        consider = responded & deliver
        present = deliver | expire
        if cfg.skip_absent_votes:
            present = present & consider

        yes_pack, consider_pack = exchange.gather_vote_packs(
            packed_prefs, peers, consider, lie,
            _delivery_key(key, d), cfg, minority_t, t)
        present_pack = jnp.broadcast_to(
            _pack_bits(present)[:, None], consider_pack.shape)
        update_mask = polled & jnp.logical_not(
            vr.has_finalized(records.confidence, cfg))
        if live_rows is not None:
            update_mask = update_mask & live_rows[:, None]
        records, ch = vr.register_packed_votes_present(
            records, yes_pack, consider_pack, present_pack, cfg.k, cfg,
            update_mask=update_mask)
        changed = changed | ch
        votes_applied = votes_applied + (
            popcount8(consider_pack).astype(jnp.int32) * update_mask).sum()
        return records, changed, votes_applied

    changed0 = jnp.zeros(records.votes.shape, jnp.bool_)
    return lax.fori_loop(0, depth, body,
                         (records, changed0, jnp.int32(0)))


def deliver_1d(
    ring: InflightState,
    records: vr.VoteRecordState,
    cfg: AvalancheConfig,
    prefs: jax.Array,
    key: jax.Array,
    round_: jax.Array,
    live_rows: Optional[jax.Array] = None,
) -> Tuple[vr.VoteRecordState, jax.Array]:
    """`deliver_multi` for single-decree Snowball (``[N]`` records).

    Same age walk, expiry semantics, and dead-querier freeze
    (`live_rows`); the response gather is a plain row gather of the
    pre-round ``[N]`` preference plane plus the 1-D adversary transform.
    Returns ``(records, changed)``.
    """
    timeout = cfg.timeout_rounds()
    depth = timeout + 1

    def body(i, carry):
        records, changed = carry
        d = jnp.int32(timeout) - i
        slot = jnp.mod(round_ - d + depth, depth)
        peers = lax.dynamic_index_in_dim(ring.peers, slot, 0, False)
        lat = lax.dynamic_index_in_dim(ring.lat, slot, 0, False)
        responded = lax.dynamic_index_in_dim(ring.responded, slot, 0, False)
        lie = lax.dynamic_index_in_dim(ring.lie, slot, 0, False)
        mask = lax.dynamic_index_in_dim(ring.polled, slot, 0, False)

        votes = adversary.apply_1d(_delivery_key(key, d), prefs[peers],
                                   lie, cfg, prefs)
        deliver = (lat == d[None, None]) & (d != timeout)
        expire = (lat >= timeout) & (d == timeout)
        consider = responded & deliver
        present = deliver | expire
        if cfg.skip_absent_votes:
            present = present & consider

        update_mask = mask & jnp.logical_not(
            vr.has_finalized(records.confidence, cfg))
        if live_rows is not None:
            update_mask = update_mask & live_rows
        records, ch = vr.register_packed_votes_present(
            records, _pack_bits(votes), _pack_bits(consider),
            _pack_bits(present), cfg.k, cfg, update_mask=update_mask)
        return records, changed | ch

    changed0 = jnp.zeros(records.votes.shape, jnp.bool_)
    return lax.fori_loop(0, depth, body, (records, changed0))


def clear_columns(ring: Optional[InflightState],
                  cols: jax.Array) -> Optional[InflightState]:
    """Drop pending updates for window columns being retired/refilled.

    The streaming schedulers (`models/backlog`, `models/streaming_dag`
    and their sharded twins) reuse window columns for NEW txs; a response
    still in flight for the old occupant must not land on its
    replacement, so every ring slot's stored poll mask drops the refilled
    columns.  `cols` is bool ``[W]`` (True = column re-assigned); None
    ring (engine off) passes through.
    """
    if ring is None:
        return None
    return ring._replace(
        polled=ring.polled & jnp.logical_not(cols)[None, None, :])
