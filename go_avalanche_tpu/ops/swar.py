"""SWAR lane packing: 4 uint8 records per 32-bit word for the VPU.

The r03 Pallas post-mortem (`ops/pallas_vote.py` docstring) and the r05
roofline (PERF_NOTES.md) agree on the ingest kernel's bottleneck: the
TPU VPU vectorizes i32 (and i16) arithmetic only, so every uint8 plane
the window update touches is widened 4x before any work happens.  The
SIMD-within-a-register answer is to make the widening the LAYOUT: pack
4 *adjacent tx columns'* uint8 values into one uint32 word, one byte
lane per column, and run the hot loop's shifts/counts/compares
lane-parallel on native i32 words — zero widening, a quarter of the
elements.

Lane layout (little-endian byte order, pinned by
`tests/test_swar.py::test_pack_lane_order_is_little_endian`):

      u32 word w                      uint8 columns
      bits [ 0:  8)  = lane 0  <->  column 4*w + 0
      bits [ 8: 16)  = lane 1  <->  column 4*w + 1
      bits [16: 24)  = lane 2  <->  column 4*w + 2
      bits [24: 32)  = lane 3  <->  column 4*w + 3

Pack/unpack are pure `lax.bitcast_convert_type` + reshape — layout
moves, not arithmetic — so the engine boundary costs nothing the
surrounding fusion doesn't already pay.  The arithmetic primitives
below are the classic SWAR idioms, each documented with its lane-safety
precondition (when a plain 32-bit op is guaranteed not to carry/borrow
across lane boundaries).

Ragged tails: a trailing axis not divisible by 4 is zero-padded at pack
time and sliced at unpack time; all-zero lanes are inert through every
primitive here (shift-in of 0, counters stay 0, compares stay false).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

LANES = 4

# Per-lane replicated constants (one byte value in every lane).
_LSB = 0x01010101   # bit 0 of every lane
_MSB = 0x80808080   # bit 7 of every lane
_NOCARRY = 0xFEFEFEFE  # everything but bit 0: masks the <<1 inter-lane carry


def lane_const(byte: int) -> np.uint32:
    """uint32 scalar with `byte` replicated into all 4 lanes.

    A NUMPY scalar on purpose (as are `LANE_LSB`/`LANE_MSB` below): a
    module-level or closure-level `jnp` scalar materializes through the
    trace machinery, so a first import that happens INSIDE a jit trace
    (e.g. `hlo_pin.py`'s abstract lowering) would leak a tracer into
    every later caller.  numpy scalars are inert constants everywhere."""
    if not (0 <= byte <= 0xFF):
        raise ValueError("lane_const takes one byte")
    return np.uint32(byte * _LSB)


def pack_u8_lanes(x: jax.Array) -> jax.Array:
    """uint8 ``[..., t]`` -> uint32 ``[..., ceil(t/4)]``, column ``4w + b``
    in byte lane ``b`` of word ``w`` (layout above).  Zero-pads a ragged
    tail; a pure bitcast otherwise."""
    x = jnp.asarray(x, jnp.uint8)
    *lead, t = x.shape
    tp = -(-t // LANES) * LANES
    if tp != t:
        x = jnp.pad(x, [(0, 0)] * len(lead) + [(0, tp - t)])
    return lax.bitcast_convert_type(
        x.reshape(*lead, tp // LANES, LANES), jnp.uint32)


def unpack_u8_lanes(w: jax.Array, t: int) -> jax.Array:
    """Inverse of `pack_u8_lanes`: uint32 ``[..., ceil(t/4)]`` -> uint8
    ``[..., t]`` (pad columns dropped)."""
    b = lax.bitcast_convert_type(w, jnp.uint8)       # [..., W, 4]
    return b.reshape(*w.shape[:-1], -1)[..., :t]


def expand_lane_mask(mask_w: jax.Array, t: int) -> jax.Array:
    """Per-lane mask word (any nonzero byte = hit) -> bool ``[..., t]``."""
    return unpack_u8_lanes(mask_w, t) != 0


def popcount8_lanes(w: jax.Array) -> jax.Array:
    """Per-BYTE-LANE popcount of a uint32 word array.

    The `bitops.popcount8` SWAR ladder on 4 lanes at once; the masks keep
    every partial sum inside its lane, so no step can carry across."""
    w = w - ((w >> 1) & jnp.uint32(0x55555555))
    w = (w & jnp.uint32(0x33333333)) + ((w >> 2) & jnp.uint32(0x33333333))
    return (w + (w >> 4)) & jnp.uint32(0x0F0F0F0F)


def lane_shl1(w: jax.Array, in_bits: jax.Array) -> jax.Array:
    """Per-lane ``(lane << 1) | in_bit``: the window shift.

    The 32-bit shift moves every lane's bit 7 into its neighbor's bit 0;
    masking with 0xFEFEFEFE drops exactly those carried bits.  `in_bits`
    must only occupy lane bit 0 (an ``& _LSB``-shaped value)."""
    return ((w << 1) & jnp.uint32(_NOCARRY)) | in_bits


def lane_gt(w: jax.Array, threshold: int) -> jax.Array:
    """Per-lane unsigned ``lane > threshold``, as an 0x80-per-hit-lane
    mask word.

    Bias-to-MSB compare: lane bit 7 of ``w + (0x7F - threshold)`` is set
    iff ``lane >= threshold + 1``.  Lane-safe while
    ``lane + 0x7F - threshold <= 0xFF`` i.e. ``lane <= 0x80 + threshold``
    — window counters (<= 8) and quorum thresholds (0..7) sit far
    inside it."""
    if not (0 <= threshold <= 0x7F):
        raise ValueError("lane_gt threshold must be in [0, 0x7F]")
    return (w + lane_const(0x7F - threshold)) & jnp.uint32(_MSB)


def lane_fill(bits: jax.Array) -> jax.Array:
    """Lane-LSB bits (an ``& _LSB``-shaped value) -> 0xFF-filled lanes.

    ``bit * 0xFF`` per lane: each product occupies exactly its own lane
    (0 or 0xFF), so the 32-bit multiply never carries between them."""
    return bits * jnp.uint32(0xFF)


LANE_LSB = np.uint32(_LSB)   # numpy, not jnp — see lane_const
LANE_MSB = np.uint32(_MSB)
