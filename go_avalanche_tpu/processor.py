"""The Processor — layer L2, the per-node poll/response engine.

Host-side engine with full API parity to the reference (`processor.go:11-248`):
target admission, vote ingest with status updates, poll construction, peer
selection, and the ticker event loop.  This is the *control-plane* twin of the
batched simulator in `models/` — correct for one node with Python-object
targets; the `[nodes, txs]` array simulators are the scale path.

Deliberate fixes over the reference, each flagged by SURVEY.md section 2.3:
  * The request/response validation contract the reference compiled out behind
    `if false` "while hacking on simulations" (`processor.go:62-90`) is an
    explicit config mode (`AvalancheConfig.strict_validation`); both modes are
    tested.
  * Poll invs are deterministically score-descending (the intended-but-disabled
    `sortBlockInvsByWork`, `processor.go:163`), not map-random.
  * The round counter actually advances per poll (the reference never
    increments `p.round`; its tests bump it by hand, `avalanche_test.go:302`).
    `advance_round=False` restores reference behavior.
  * Peer selection honors an availability timer in strict mode (nodes with an
    outstanding unexpired request are not re-queried) — the TODO the reference
    tests carry (`avalanche_test.go:453-454, 277`) — and supports random
    selection in place of always-lowest-ID (`processor.go:173-182`).
  * Public methods are internally locked; the reference requires caller-side
    mutexes (`processor.go:21`, example `main.go:76`).
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional, Set, Tuple

from go_avalanche_tpu.clock import Clock
from go_avalanche_tpu.config import AvalancheConfig, DEFAULT_CONFIG
from go_avalanche_tpu.net import Connman
from go_avalanche_tpu.types import (
    NO_NODE,
    Hash,
    Inv,
    NodeID,
    RequestRecord,
    Response,
    StatusUpdate,
    Target,
    normalize_err,
    sort_invs_by_score,
)
from go_avalanche_tpu.utils.golden import ScalarVoteRecord


class Processor:
    """Drives the Avalanche process: sends queries, handles responses.

    (`processor.go:11-37`.)
    """

    def __init__(
        self,
        connman: Connman,
        cfg: AvalancheConfig = DEFAULT_CONFIG,
        clock: Optional[Clock] = None,
        advance_round: bool = True,
        node_selection: str = "lowest",
        seed: int = 0,
    ) -> None:
        if node_selection not in ("lowest", "random"):
            raise ValueError("node_selection must be 'lowest' or 'random'")
        self._connman = connman
        self._cfg = cfg
        self._clock = clock if clock is not None else Clock()
        self._advance_round = advance_round
        self._node_selection = node_selection
        self._rng = random.Random(seed)

        self._round: int = 0
        self._targets: Dict[Hash, Target] = {}
        self._vote_records: Dict[Hash, ScalarVoteRecord] = {}
        self._node_ids: Set[NodeID] = set()
        self._queries: Dict[Tuple[int, NodeID], RequestRecord] = {}

        self._mu = threading.RLock()
        self._run_mu = threading.Lock()
        self._running = False
        self._stop_evt: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ state

    def get_round(self) -> int:
        """Current poll round (`processor.go:40-42`)."""
        with self._mu:
            return self._round

    def add_target_to_reconcile(self, t: Target) -> bool:
        """Begin voting on a target (`processor.go:45-58`).

        Idempotent; rejects invalid targets; seeds the record with the
        target's own initial preference.
        """
        with self._mu:
            if not self._is_worthy_polling(t):
                return False
            if t.hash() in self._vote_records:
                return False
            self._targets[t.hash()] = t
            self._vote_records[t.hash()] = ScalarVoteRecord.new(
                t.is_accepted(), self._cfg)
            return True

    def register_votes(self, node_id: NodeID, resp: Response,
                       updates: List[StatusUpdate]) -> bool:
        """Ingest a query response (`processor.go:61-122`).

        Appends one StatusUpdate per state change to `updates` and deletes
        finalized records.  In strict mode the response must answer an
        outstanding, unexpired request from `node_id` for exactly the polled
        invs, in order (`processor.go:64-89`).
        """
        with self._mu:
            if not self._cfg.strict_validation:
                # Opportunistically consume a matching pending query so the
                # queries dict stays bounded in sim mode too (the reference
                # leaks these; it only avoids unbounded growth because its
                # round never advances and the key is overwritten in place).
                self._queries.pop((resp.get_round(), node_id), None)
            else:
                key = (resp.get_round(), node_id)
                record = self._queries.pop(key, None)  # always consume the key
                if record is None:
                    return False
                if record.is_expired(self._clock.now(),
                                     self._cfg.request_timeout_s):
                    return False
                invs = record.get_invs()
                votes = resp.get_votes()
                if len(votes) != len(invs):
                    return False
                for inv, vote in zip(invs, votes):
                    if inv.target_hash != vote.get_hash():
                        return False

            for vote in resp.get_votes():
                vr = self._vote_records.get(vote.get_hash())
                if vr is None:
                    continue  # not voting on this anymore
                if not self._is_worthy_polling(self._targets[vote.get_hash()]):
                    continue
                if not vr.register_vote(normalize_err(vote.get_error())):
                    continue  # vote provided no extra information
                updates.append(StatusUpdate(vote.get_hash(), vr.status()))
                if vr.has_finalized():
                    del self._vote_records[vote.get_hash()]

            self._node_ids.add(node_id)
            return True

    def is_accepted(self, t: Target) -> bool:
        """Current acceptance of a target (`processor.go:125-130`).

        Unknown targets report False (including finalized-accepted ones whose
        records were removed — reference behavior).
        """
        with self._mu:
            vr = self._vote_records.get(t.hash())
            return vr.is_accepted() if vr is not None else False

    def get_confidence(self, t: Target) -> int:
        """Confidence in the target's current state (`processor.go:133-140`).

        Raises KeyError for unknown targets (the reference panics).
        """
        with self._mu:
            vr = self._vote_records.get(t.hash())
            if vr is None:
                raise KeyError(f"VoteRecord not found for hash {t.hash()}")
            return vr.get_confidence()

    # ------------------------------------------------------------------ polls

    def get_invs_for_next_poll(self) -> List[Inv]:
        """Invs for outstanding targets needing more votes
        (`processor.go:144-170`): skip finalized and invalid, order
        score-descending, cap at `max_element_poll`."""
        with self._mu:
            invs = []
            for h, vr in self._vote_records.items():
                if vr.has_finalized():
                    continue
                t = self._targets[h]
                if not self._is_worthy_polling(t):
                    continue
                invs.append(Inv(t.type(), h))
            invs = sort_invs_by_score(invs, self._targets)
            return invs[: self._cfg.max_element_poll]

    def get_suitable_node_to_query(self) -> NodeID:
        """Pick the peer for the next query (`processor.go:173-182`).

        'lowest' reproduces the reference placeholder (sorted, first);
        'random' is the protocol-correct uniform draw.  In strict mode, peers
        with an outstanding unexpired request are unavailable until they
        answer or the request expires.
        """
        with self._mu:
            candidates = self._available_nodes()
            if not candidates:
                return NO_NODE
            if self._node_selection == "random":
                return self._rng.choice(candidates)
            return candidates[0]

    def event_loop(self) -> None:
        """One tick (`processor.go:235-243`): snapshot the poll and record the
        pending query; transport is the caller's job.  Advances the round per
        poll when `advance_round` (the reference never does,
        SURVEY.md section 2.3)."""
        with self._mu:
            self._reap_expired_queries()
            invs = self.get_invs_for_next_poll()
            if not invs:
                return
            node_id = self.get_suitable_node_to_query()
            if node_id == NO_NODE:
                return
            self._queries[(self._round, node_id)] = RequestRecord(
                self._clock.now(), invs)
            if self._advance_round:
                self._round += 1

    # -------------------------------------------------------------- lifecycle

    def start(self) -> bool:
        """Begin the ticker loop (`processor.go:190-216`); False if running."""
        with self._run_mu:
            if self._running:
                return False
            self._running = True
            self._stop_evt = threading.Event()

            def _loop(stop: threading.Event) -> None:
                while not stop.wait(self._cfg.time_step_s):
                    self.event_loop()

            self._thread = threading.Thread(
                target=_loop, args=(self._stop_evt,), daemon=True)
            self._thread.start()
            return True

    def stop(self) -> bool:
        """Stop the ticker loop (`processor.go:219-232`); False if stopped."""
        with self._run_mu:
            if not self._running:
                return False
            assert self._stop_evt is not None and self._thread is not None
            self._stop_evt.set()
            self._thread.join()
            self._running = False
            return True

    # ------------------------------------------------------------- internals

    def _reap_expired_queries(self) -> None:
        """Drop expired pending queries so `_queries` stays bounded by the
        request timeout even for peers that never answer."""
        now = self._clock.now()
        expired = [k for k, r in self._queries.items()
                   if r.is_expired(now, self._cfg.request_timeout_s)]
        for k in expired:
            del self._queries[k]

    def _is_worthy_polling(self, t: Target) -> bool:
        """Polling is pointless for invalid targets (`processor.go:185-187`)."""
        return t.is_valid()

    def _available_nodes(self) -> List[NodeID]:
        node_ids = sorted(self._connman.nodes_ids())
        if not self._cfg.strict_validation:
            return node_ids
        now = self._clock.now()
        busy = {
            nid
            for (_, nid), record in self._queries.items()
            if not record.is_expired(now, self._cfg.request_timeout_s)
        }
        return [n for n in node_ids if n not in busy]

    def outstanding_requests(self) -> int:
        """Number of recorded, unanswered queries (observability helper)."""
        with self._mu:
            return len(self._queries)

    # Reference-spelling aliases for drop-in familiarity.
    GetRound = get_round
    AddTargetToReconcile = add_target_to_reconcile
    RegisterVotes = register_votes
    IsAccepted = is_accepted
    GetConfidence = get_confidence
    GetInvsForNextPoll = get_invs_for_next_poll
