"""go_avalanche_tpu — a TPU-native Avalanche consensus simulation framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of
`itsdevbear/go-avalanche` (see SURVEY.md): the Snowball vote-record state
machine, the poll/response Processor, and a peer network simulator — rebuilt
as batched array computation.  Layers:

  ops/       L0 — the vectorized vote-record kernel (+ Pallas fusion)
  (this pkg) L1 — wire/data types, config, clock
  processor  L2 — host-side per-node Processor with full reference API parity
  net        L3 — Connman peer registry
  models/    L4 — batched network simulators (slush, snowflake, snowball,
             avalanche, conflict DAG, streaming backlog, streaming
             conflict-DAG — the north-star composition, node-axis
             streaming over a stake registry)
  stake      stake distributions + registry working-set draws
  parallel/  mesh + shard_map sharding of the simulators
  utils/     golden oracle, checkpointing, metrics
"""

from go_avalanche_tpu.config import (
    AdversaryStrategy,
    AvalancheConfig,
    DEFAULT_CONFIG,
    VoteMode,
)
from go_avalanche_tpu.clock import Clock, StubClock
from go_avalanche_tpu.net import Connman
from go_avalanche_tpu.processor import Processor
from go_avalanche_tpu.types import (
    NO_NODE,
    VOTE_NEUTRAL,
    VOTE_NO,
    VOTE_YES,
    Block,
    Hash,
    Inv,
    NodeID,
    RequestRecord,
    Response,
    Status,
    StatusUpdate,
    Target,
    Tx,
    Vote,
)

__version__ = "0.1.0"

__all__ = [
    "AdversaryStrategy",
    "AvalancheConfig",
    "DEFAULT_CONFIG",
    "VoteMode",
    "Clock",
    "StubClock",
    "Connman",
    "Processor",
    "NO_NODE",
    "VOTE_NEUTRAL",
    "VOTE_NO",
    "VOTE_YES",
    "Block",
    "Hash",
    "Inv",
    "NodeID",
    "RequestRecord",
    "Response",
    "Status",
    "StatusUpdate",
    "Target",
    "Tx",
    "Vote",
]
