"""Clock abstraction for testable time.

The reference swaps a package-global `clock clocker` for a stub in tests
(`avalanche.go:93-108`) — and never restores it, a test-pollution hazard the
survey flags (SURVEY.md section 4).  Here the clock is an instance owned by
each Processor, injected at construction, so tests cannot pollute each other.
"""

from __future__ import annotations

import time


class Clock:
    """Real wall clock (`avalanche.go:100-103`)."""

    def now(self) -> float:
        return time.time()


class StubClock(Clock):
    """Settable clock for tests (`avalanche.go:105-108`), plus `advance`."""

    def __init__(self, t: float = 0.0) -> None:
        self._t = t

    def now(self) -> float:
        return self._t

    def set(self, t: float) -> None:
        self._t = t

    def advance(self, dt: float) -> None:
        self._t += dt
