"""Monte-Carlo fleet driver: whole-sim vmap over a batched seed axis.

"Quantifying Liveness and Safety of Avalanche's Snowball"
(arXiv:2409.02217) and "An Analysis of Avalanche Consensus"
(arXiv:2401.02811) derive failure probabilities as functions of
(k, quorum, byzantine fraction, adversary strategy) — exactly the axes
`AvalancheConfig` exposes.  This module turns one simulated trajectory
into a STATISTICAL GUARANTEE: `run_fleet` vmaps an **entire sim** —
init from a per-trial key, the full `round_step` scan, and the in-graph
safety/finality reduction — over a batched seed axis, so a fleet of
``F`` trials is ONE compiled program over ``[F, N, T]`` planes (one
compile per config point; config axes sweep by re-jit, the seed axis
batches in-graph).  A fleet of 1024 small sims is also the ideal
dispatch-amortization workload (`bench.py --fleet`).

What a trial reports (`TrialOutcome`, reduced in-graph to scalars):

  * **safety violation** — the papers' safety event, detected on the
    final state among HONEST nodes only (byzantine rows may "finalize"
    anything; the protocol's guarantee is about correct nodes):
    snowball = quorum divergence (two honest nodes finalized opposite
    colors); avalanche = any tx finalized accepted by one honest node
    and rejected by another; dag = two txs of one conflict set both
    finalized ACCEPTED somewhere among honest nodes (a double-spend
    committed twice);
  * **settled** + **finality round** — did every honest record (set,
    for the DAG) finalize within the horizon, and the round the LAST
    one landed (-1 while unsettled): the per-trial finality capture
    behind E(finality) and its CI;
  * the realized stochastic fault windows (`cfg.stochastic_events()`,
    `ops/inflight.draw_fault_params`) so per-trial recovery checking
    (`obs.recovery.verify_recovery(..., windows=...)`) knows each
    trial's actual schedule.

Fleet estimates carry **Wilson confidence intervals**
(`wilson_interval`) — the phase-diagram numbers are P(violation) /
P(settled) with CIs that behave at 0 and 1 (a 512-trial fleet with no
violations excludes rates above ~0.75%, which is what makes "safe at
this config point" a checkable claim rather than an anecdote).

Phase diagrams: `run_phase_grid` sweeps a validated axis grid
(`phase_points`) by re-jit, one fleet per point, and streams one JSONL
row per point through the `obs` sink with `tag_from_config` tags —
the phase-diagram format documented in docs/observability.md.

    from go_avalanche_tpu import fleet
    res = fleet.run_fleet("snowball", cfg, fleet=512, n_nodes=64,
                          n_rounds=120)
    res.p_violation, res.violation_ci     # P(safety violation) + CI

    rows = fleet.run_phase_grid(
        "snowball", cfg, {"byzantine_fraction": [0.0, 0.2, 0.4]},
        fleet=512, n_nodes=64, n_rounds=120)

vmap-cleanliness contract (the PR 7 audit): every model's init/run
path is free of data-dependent Python branching — statics come from
the config and shapes, never from traced values — pinned by the
`vmap(run_scan)` == stacked-individual-runs bit-parity tests
(tests/test_fleet.py, all three inflight engines, dense + sharded).
`cfg.metrics_every` must be 0 here: the in-graph tap's io_callback
has no per-trial identity under vmap (phase rows stream host-side
through the sink instead).  Round-by-round PER-TRIAL telemetry comes
from the on-device trace plane instead (`cfg.trace_every > 0`,
obs/trace.py): the vmap lifts each trial's ``[S, M]`` buffer to an
``[F, S, M]`` stack (`FleetResult.trace` / `trace_records()`), which
`obs.check_recovery` consumes for per-trial recovery verdicts against
each trial's realized fault windows.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import math
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from go_avalanche_tpu.config import (
    ADVERSARY_POLICIES,
    AdversaryStrategy,
    AvalancheConfig,
)
from go_avalanche_tpu.ops import voterecord as vr

FLEET_MODELS = ("snowball", "avalanche", "dag", "backlog")


# --------------------------------------------------------------------------
# Wilson confidence interval — the fleet's one spelling of "how sure".


def wilson_interval(successes: int, trials: int,
                    z: float = 1.96) -> Tuple[float, float]:
    """Wilson score interval for a binomial proportion; (lo, hi).

    Chosen over the normal approximation because the phase diagram's
    interesting points sit at the extremes: 0 successes gives a
    non-degenerate upper bound (z²/(n+z²) ≈ 0.75% at n=512) and any
    success count >= 1 gives a strictly positive lower bound — exactly
    the "CI excludes 0" / "CI excludes rates above x%" claims the
    acceptance pins make.
    """
    if trials <= 0:
        raise ValueError(f"wilson_interval needs trials >= 1, got {trials}")
    if not (0 <= successes <= trials):
        raise ValueError(f"successes {successes} outside [0, {trials}]")
    p = successes / trials
    z2 = z * z
    denom = 1.0 + z2 / trials
    center = (p + z2 / (2 * trials)) / denom
    half = (z * math.sqrt(p * (1 - p) / trials + z2 / (4 * trials * trials))
            / denom)
    return max(0.0, center - half), min(1.0, center + half)


# --------------------------------------------------------------------------
# In-graph safety-violation detectors (final-state reductions).


def snowball_safety_violated(state, cfg: AvalancheConfig) -> jax.Array:
    """Quorum divergence: two HONEST nodes finalized opposite colors.

    Scalar bool, in-graph.  Byzantine rows are excluded — the papers'
    safety property quantifies over correct nodes (an adversary
    "finalizing" both colors is its prerogative, not a protocol
    failure).
    """
    fin = vr.has_finalized(state.records.confidence, cfg)
    acc = vr.is_accepted(state.records.confidence)
    honest_fin = fin & jnp.logical_not(state.byzantine)
    return (honest_fin & acc).any() & (honest_fin & ~acc).any()


def avalanche_safety_violated(state, cfg: AvalancheConfig) -> jax.Array:
    """Per-target divergence: some tx finalized ACCEPTED by one honest
    node and REJECTED by another.  Scalar bool, in-graph."""
    fin = vr.has_finalized(state.records.confidence, cfg)
    acc = vr.is_accepted(state.records.confidence)
    honest = jnp.logical_not(state.byzantine)[:, None]
    yes = (fin & acc & honest).any(axis=0)          # [T]
    no = (fin & ~acc & honest).any(axis=0)
    return (yes & no).any()


def dag_safety_violated(state, cfg: AvalancheConfig) -> jax.Array:
    """Conflict-set double-finalize: two txs of ONE conflict set both
    finalized ACCEPTED somewhere among honest nodes — the committed
    double-spend.  Scalar bool, in-graph; cross-node counts (node A
    commits tx1, node B commits its rival) are violations too, which is
    why the reduction ORs over nodes BEFORE counting per set."""
    base = state.base
    fin_acc = (vr.has_finalized(base.records.confidence, cfg)
               & vr.is_accepted(base.records.confidence))
    honest = jnp.logical_not(base.byzantine)[:, None]
    committed_t = (fin_acc & honest).any(axis=0)    # [T]
    if state.set_size is not None:
        t = committed_t.shape[0]
        per_set = committed_t.reshape(t // state.set_size,
                                      state.set_size).sum(axis=1)
    else:
        per_set = jax.ops.segment_sum(committed_t.astype(jnp.int32),
                                      state.conflict_set,
                                      num_segments=state.n_sets)
    return (per_set >= 2).any()


def liveness_stalled(finalized: jax.Array, byzantine: jax.Array,
                     alive: jax.Array) -> jax.Array:
    """The in-graph LIVENESS/stall detector — the complement of the
    safety detectors above: an honest-majority network that still
    finalized NOTHING by the horizon has been denied progress (the
    arXiv 2401.02811 stall; 2409.02217's liveness-failure event).

    Scalar bool, per trial under the fleet vmap.  `finalized` is the
    final-state `vr.has_finalized` plane (``[N]`` or ``[N, T]``; any
    polarity — a stalled network finalizes nothing at all), `byzantine`
    / `alive` the final bool ``[N]`` planes.  Two byzantine exclusions,
    mirroring the safety detectors' honest-only quantification:

      * only HONEST finalizations count as progress — an adversary
        "finalizing" its own records proves nothing about liveness;
      * the verdict only fires while live honest nodes still hold a
        majority of the population — a network the adversary + churn
        actually overwhelmed has no liveness guarantee to violate, so
        reporting it as a detected stall would inflate P(stall) with
        trials outside the theorem's hypothesis.
    """
    honest = jnp.logical_not(byzantine)
    majority = (honest & alive).sum() * 2 > byzantine.shape[0]
    fin_rows = finalized if finalized.ndim == 1 else finalized.any(axis=1)
    return majority & jnp.logical_not((fin_rows & honest).any())


class TrialOutcome(NamedTuple):
    """One fleet trial's in-graph reduction (scalars; ``[F]``-stacked
    under the fleet vmap)."""

    violation: jax.Array          # bool — safety violated at the horizon
    settled: jax.Array            # bool — every honest record/set final
    finality_round: jax.Array     # int32 — round the LAST honest record
                                  #   finalized; -1 while unsettled
    finalized_fraction: jax.Array  # float32 — honest records finalized
    stalled: jax.Array            # bool — honest majority exists yet no
                                  #   honest record finalized by the
                                  #   horizon (`liveness_stalled`)
    cut_start: Optional[jax.Array] = None  # int32 [Ec] realized windows
    cut_end: Optional[jax.Array] = None    # (None: no stochastic cuts)
    cut_split: Optional[jax.Array] = None  # int32 [Ec] realized node
                                  #   split of each stochastic cut
    spike_start: Optional[jax.Array] = None  # int32 [Es] realized
    spike_end: Optional[jax.Array] = None    #   stochastic_spike windows
    spike_extra: Optional[jax.Array] = None  #   + extra rounds (None: no
                                  #   stochastic spikes scheduled)
    lat_p50: Optional[jax.Array] = None   # int32 — finality-latency
    lat_p99: Optional[jax.Array] = None   #   percentiles of the traffic
    lat_p999: Optional[jax.Array] = None  #   plane (backlog model with
                                  #   arrivals on; None otherwise)
    arrived: Optional[jax.Array] = None   # int32 — units arrived
    region_start: Optional[jax.Array] = None  # int32 [Er] realized
    region_end: Optional[jax.Array] = None    #   stochastic_regional_
    region_cluster: Optional[jax.Array] = None  # outage windows + the
                                  #   drawn severed cluster (None: none
                                  #   scheduled)


def _fault_realizations(fault_params) -> Dict:
    """TrialOutcome kwargs capturing the trial's REALIZED stochastic
    fault schedule (`ops/inflight.draw_fault_params`) — cut windows WITH
    their node splits and spike windows with their extra rounds, so a
    phase-diagram row can record exactly what each trial experienced
    (ROADMAP PR-7 follow-up; rendered by `FleetResult.realizations`)."""
    if fault_params is None:
        return {}
    return dict(cut_start=fault_params.cut_start,
                cut_end=fault_params.cut_end,
                cut_split=fault_params.cut_split,
                spike_start=fault_params.spike_start,
                spike_end=fault_params.spike_end,
                spike_extra=fault_params.spike_extra,
                region_start=fault_params.region_start,
                region_end=fault_params.region_end,
                region_cluster=fault_params.region_cluster)


def _outcome_snowball(state, cfg: AvalancheConfig) -> TrialOutcome:
    fin = vr.has_finalized(state.records.confidence, cfg)
    honest = jnp.logical_not(state.byzantine)
    settled = (fin | ~honest).all()
    stamped = jnp.where(honest & fin, state.finalized_at, -1)
    return TrialOutcome(
        violation=snowball_safety_violated(state, cfg),
        settled=settled,
        finality_round=jnp.where(settled, stamped.max(), jnp.int32(-1)),
        finalized_fraction=(fin & honest).sum() / honest.sum(),
        stalled=liveness_stalled(fin, state.byzantine, state.alive),
        **_fault_realizations(state.fault_params))


def _outcome_avalanche(state, cfg: AvalancheConfig) -> TrialOutcome:
    fin = vr.has_finalized(state.records.confidence, cfg)
    honest = jnp.logical_not(state.byzantine)[:, None]
    settled = (fin | ~honest).all()
    stamped = jnp.where(honest & fin, state.finalized_at, -1)
    return TrialOutcome(
        violation=avalanche_safety_violated(state, cfg),
        settled=settled,
        finality_round=jnp.where(settled, stamped.max(), jnp.int32(-1)),
        finalized_fraction=((fin & honest).sum()
                            / honest.sum() / fin.shape[1]),
        stalled=liveness_stalled(fin, state.byzantine, state.alive),
        **_fault_realizations(state.fault_params))


def _outcome_dag(state, cfg: AvalancheConfig) -> TrialOutcome:
    from go_avalanche_tpu.models import dag as dag_model

    base = state.base
    fin_acc = (vr.has_finalized(base.records.confidence, cfg)
               & vr.is_accepted(base.records.confidence))
    honest = jnp.logical_not(base.byzantine)[:, None]
    if state.set_size is not None:
        resolved = dag_model.set_any_fixed(fin_acc, state.set_size)
        n_sets_f = fin_acc.shape[1] // state.set_size
    else:
        set_done = jax.ops.segment_max(fin_acc.astype(jnp.uint8).T,
                                       state.conflict_set,
                                       num_segments=state.n_sets)
        resolved = set_done.T[:, state.conflict_set] > 0
        n_sets_f = state.n_sets
    settled = (resolved | ~honest).all()
    stamped = jnp.where(honest & fin_acc, base.finalized_at, -1)
    # resolved is per (node, tx); fraction counts (honest node, set)
    # pairs with a committed winner.
    if state.set_size is not None:
        n, t = resolved.shape
        per_set = resolved.reshape(n, n_sets_f, state.set_size).any(axis=2)
    else:
        per_set = (jax.ops.segment_max(resolved.astype(jnp.uint8).T,
                                       state.conflict_set,
                                       num_segments=state.n_sets).T > 0)
    honest_rows = jnp.logical_not(base.byzantine)
    frac = ((per_set & honest_rows[:, None]).sum()
            / honest_rows.sum() / n_sets_f)
    return TrialOutcome(
        violation=dag_safety_violated(state, cfg),
        settled=settled,
        finality_round=jnp.where(settled, stamped.max(), jnp.int32(-1)),
        finalized_fraction=frac,
        # Any-polarity finalization counts as progress (a resolved set
        # finalizes its winner accepted and may finalize rivals
        # rejected); a stalled DAG finalizes neither.
        stalled=liveness_stalled(
            vr.has_finalized(base.records.confidence, cfg),
            base.byzantine, base.alive),
        **_fault_realizations(base.fault_params))


def _outcome_backlog(state, cfg: AvalancheConfig) -> TrialOutcome:
    """Streaming-backlog trial reduction: did the whole backlog drain
    within the horizon, when did the last tx settle, and — with the
    live-traffic plane on — what finality-latency percentiles did the
    offered load produce (the capacity-planning outcome,
    `examples/capacity_planning.py`).  Safety is the avalanche per-tx
    divergence detector on the live window."""
    from go_avalanche_tpu import traffic as tf

    out = state.outputs
    settled = out.settled.all()
    lat = {}
    if state.traffic is not None:
        (p50n, p50d), (p99n, p99d), (p999n, p999d) = tf.PERCENTILES
        hist = state.traffic.lat_hist
        lat = dict(
            lat_p50=tf.percentile_from_hist(hist, p50n, p50d),
            lat_p99=tf.percentile_from_hist(hist, p99n, p99d),
            lat_p999=tf.percentile_from_hist(hist, p999n, p999d),
            arrived=state.traffic.arrived_idx)
    return TrialOutcome(
        violation=avalanche_safety_violated(state.sim, cfg),
        settled=settled,
        finality_round=jnp.where(settled, out.settle_round.max(),
                                 jnp.int32(-1)),
        finalized_fraction=out.settled.mean().astype(jnp.float32),
        # A harvested settled tx is progress even after its window slot
        # recycled, so the stream-level stall gates on BOTH planes.
        stalled=(liveness_stalled(
            vr.has_finalized(state.sim.records.confidence, cfg),
            state.sim.byzantine, state.sim.alive)
            & jnp.logical_not(out.settled.any())),
        **_fault_realizations(state.sim.fault_params),
        **lat)


# --------------------------------------------------------------------------
# The fleet program: vmap(init -> scan(round_step) -> reduce) over keys.


def _trial_fn(model: str, cfg: AvalancheConfig, n_nodes: int,
              n_txs: int, n_rounds: int, conflict_size: int,
              yes_fraction: float, contested: bool, window: int):
    """The per-key whole-sim trial program: ``key -> (TrialOutcome,
    telemetry [R], trace [S, M] | None)`` — init, the full `round_step`
    scan and the in-graph outcome reduction, nothing else.  ONE
    closure, shared by the dense fleet (`_compiled_fleet` vmaps it) and
    the trial-sharded fleet (`parallel/sharded_fleet.fleet_driver_
    program` vmaps each device's key slice): the dense-vs-sharded
    bit-parity is a refactoring invariant, not two copies kept in
    sync."""

    def trial(key):
        if model == "snowball":
            from go_avalanche_tpu.models import snowball as sb

            state = sb.with_trace(
                sb.init(key, n_nodes, cfg, yes_fraction=yes_fraction),
                cfg, n_rounds)
            step, outcome = sb.round_step, _outcome_snowball
            trace_of = lambda s: s.trace                    # noqa: E731
        elif model == "avalanche":
            from go_avalanche_tpu.models import avalanche as av

            init_pref = (av.contested_init_pref_from_key(key, n_nodes,
                                                         n_txs)
                         if contested else None)
            state = av.with_trace(
                av.init(key, n_nodes, n_txs, cfg, init_pref=init_pref),
                cfg, n_rounds)
            step, outcome = av.round_step, _outcome_avalanche
            trace_of = lambda s: s.trace                    # noqa: E731
        elif model == "backlog":
            from go_avalanche_tpu.models import backlog as bl

            # The backlog (arrival-stream order) is shared across
            # trials; only the sim/traffic key varies per trial.  A
            # final harvest pass records the last window's outcomes —
            # and their finality latencies — like `bl.run` does.
            state = bl.with_trace(
                bl.init(key, n_nodes, window,
                        bl.make_backlog(
                            jnp.arange(n_txs, dtype=jnp.int32)), cfg),
                cfg, n_rounds)

            def bl_step(s, c):
                return bl.step(s, c)

            def bl_outcome(final, c):
                final, _ = bl._retire_and_refill(final, c, refill=False)
                return _outcome_backlog(final, c)

            step, outcome = bl_step, bl_outcome
            trace_of = lambda s: s.sim.trace                # noqa: E731
        else:
            from go_avalanche_tpu.models import dag as dag_model

            state = dag_model.with_trace(
                dag_model.init(
                    key, n_nodes,
                    jnp.arange(n_txs, dtype=jnp.int32) // conflict_size,
                    cfg, n_sets=n_txs // conflict_size,
                    set_size=conflict_size),
                cfg, n_rounds)
            step, outcome = dag_model.round_step, _outcome_dag
            trace_of = lambda s: s.base.trace               # noqa: E731

        def body(s, _):
            new_s, tel = step(s, cfg)
            return new_s, tel

        final, tel = lax.scan(body, state, None, length=n_rounds)
        return outcome(final, cfg), tel, trace_of(final)

    return trial


@functools.lru_cache(maxsize=16)  # bounded, like models/avalanche's jits
def _compiled_fleet(model: str, cfg: AvalancheConfig, n_nodes: int,
                    n_txs: int, n_rounds: int, conflict_size: int,
                    yes_fraction: float, contested: bool, window: int):
    """One jitted ``keys [F] -> (TrialOutcome [F], telemetry [F, R],
    trace [F, S, M] | None)`` program — the whole sim (init included)
    lives inside the vmap, so a fleet is one compile and one dispatch
    per config point.  With `cfg.trace_every > 0` each trial carries
    its own on-device trace plane (obs/trace.py) — the vmap lifts the
    ``[S, M]`` buffer to PER-TRIAL ``[F, S, M]`` traces, which is what
    the in-graph metrics tap could never do (an io_callback has no
    per-trial identity under vmap)."""
    return jax.jit(jax.vmap(_trial_fn(
        model, cfg, n_nodes, n_txs, n_rounds, conflict_size,
        yes_fraction, contested, window)))


@functools.lru_cache(maxsize=16)
def _compiled_sharded_fleet(model: str, cfg: AvalancheConfig,
                            n_nodes: int, n_txs: int, n_rounds: int,
                            conflict_size: int, yes_fraction: float,
                            contested: bool, window: int, mesh):
    """The trial-SHARDED twin of `_compiled_fleet`: the same per-trial
    program laid over a fleet mesh (`parallel/sharded_fleet`) — keys
    sharded ``P(('trials', 'nodes'))``, each device vmapping its F/D
    slice, per-trial vectors all-gathered and summary counts psum'd
    in-graph.  Keyed on the mesh too (`jax.sharding.Mesh` hashes by
    device grid + axis names), so a phase grid re-jits per config point
    exactly like the dense cache — the retrace guard
    (`analysis/retrace.guard_fleet_point`) reads whichever cache the
    point's mesh selects."""
    from go_avalanche_tpu.parallel import sharded_fleet

    return sharded_fleet.fleet_driver_program(mesh, _trial_fn(
        model, cfg, n_nodes, n_txs, n_rounds, conflict_size,
        yes_fraction, contested, window))


def _fleet_cache(mesh):
    """The compiled-program cache a (mesh | None) selection uses — the
    one dispatch spelling shared by `run_fleet`, `run_phase_grid`'s
    retrace guard and `run_sim --audit`.  A 1-device mesh COLLAPSES to
    the dense program (the off-path identity `hlo_pin.py
    --verify-off-path` pins for the bench twin)."""
    from go_avalanche_tpu.parallel import sharded_fleet

    return (_compiled_sharded_fleet
            if sharded_fleet.mesh_devices(mesh) > 1 else _compiled_fleet)


def compiled_fleet_program(model: str, cfg: AvalancheConfig,
                           n_nodes: int, n_txs: int, n_rounds: int,
                           conflict_size: int, yes_fraction: float,
                           contested: bool, window: int, mesh=None):
    """The jitted fleet program a (config point, mesh | None) selection
    executes — dense vmap or the trial-sharded driver.  `run_sim
    --audit` / `--report-memory` lower through THIS (the lru-cached
    jits the run executes), so the audited program compiles exactly
    once at execution."""
    cache = _fleet_cache(mesh)
    args = (model, cfg, int(n_nodes), int(n_txs), int(n_rounds),
            int(conflict_size), float(yes_fraction), bool(contested),
            int(window))
    return cache(*args) if cache is _compiled_fleet else cache(*args,
                                                               mesh)


@dataclasses.dataclass
class FleetResult:
    """Host-side reduction of one fleet: per-trial vectors plus the
    Wilson-CI estimates the phase diagram plots."""

    model: str
    fleet: int
    rounds: int
    violations: np.ndarray          # bool [F]
    settled: np.ndarray             # bool [F]
    finality_round: np.ndarray      # int32 [F]; -1 where unsettled
    finalized_fraction: np.ndarray  # float32 [F]
    stalled: np.ndarray             # bool [F] — liveness_stalled verdicts
    telemetry: object               # stacked telemetry pytree [F, R]
    cut_windows: Optional[np.ndarray]  # int32 [F, Ec, 2] realized
                                    #   stochastic [start, end) windows
    cut_split: Optional[np.ndarray] = None  # int32 [F, Ec] realized
                                    #   node split per cut
    spike_windows: Optional[np.ndarray] = None
                                    # int32 [F, Es, 3] realized
                                    #   stochastic_spike (start, end,
                                    #   extra) triples
    region_windows: Optional[np.ndarray] = None
                                    # int32 [F, Er, 3] realized
                                    #   stochastic_regional_outage
                                    #   (start, end, cluster) triples
    lat_percentiles: Optional[np.ndarray] = None
                                    # int32 [F, 3] per-trial finality-
                                    #   latency (p50, p99, p999); the
                                    #   backlog model's traffic plane
    arrived: Optional[np.ndarray] = None  # int32 [F] units arrived
    trace: Optional[object] = None  # per-trial trace plane
                                    #   (obs.trace.TraceBuffer with
                                    #   [F, S, M] data) when
                                    #   cfg.trace_every > 0 — decode
                                    #   with `trace_records()`; None
                                    #   otherwise
    p_violation: float = 0.0
    violation_ci: Tuple[float, float] = (0.0, 0.0)
    p_settled: float = 0.0
    settled_ci: Tuple[float, float] = (0.0, 0.0)
    p_stall: float = 0.0
    stall_ci: Tuple[float, float] = (0.0, 0.0)
    finality_mean: Optional[float] = None   # over settled trials
    finality_ci: Optional[Tuple[float, float]] = None

    def summary(self) -> Dict:
        """The phase-diagram JSONL row body (docs/observability.md)."""
        row = {
            "model": self.model,
            "fleet": self.fleet,
            "rounds": self.rounds,
            "violations": int(self.violations.sum()),
            "p_violation": round(self.p_violation, 6),
            "violation_ci": [round(x, 6) for x in self.violation_ci],
            "p_settled": round(self.p_settled, 6),
            "settled_ci": [round(x, 6) for x in self.settled_ci],
            "stalls": int(self.stalled.sum()),
            "p_stall": round(self.p_stall, 6),
            "stall_ci": [round(x, 6) for x in self.stall_ci],
            "finality_mean": (None if self.finality_mean is None
                              else round(self.finality_mean, 3)),
            "finality_ci": (None if self.finality_ci is None else
                            [round(x, 3) for x in self.finality_ci]),
            "finalized_fraction_mean": round(
                float(self.finalized_fraction.mean()), 6),
        }
        if self.lat_percentiles is not None:
            # Capacity-planning view (backlog model, traffic plane on):
            # per-trial nearest-rank percentiles reduced across the
            # fleet — the SLO claim is usually about lat_p99_max (the
            # worst trial must still meet the SLO).  Trials that
            # settled NOTHING within the horizon carry the -1 empty-
            # histogram sentinel; they are excluded from the latency
            # reduction (lat_trials records how many counted — an
            # overload point with lat_trials < fleet is itself an SLO
            # failure signal, never a deflated mean).
            lp = self.lat_percentiles
            valid = lp[:, 0] >= 0
            row["lat_trials"] = int(valid.sum())
            if valid.any():
                lv = lp[valid]
                row.update({
                    "lat_p50_mean": round(float(lv[:, 0].mean()), 3),
                    "lat_p99_mean": round(float(lv[:, 1].mean()), 3),
                    "lat_p999_mean": round(float(lv[:, 2].mean()), 3),
                    "lat_p99_max": int(lv[:, 1].max()),
                })
            else:
                row.update({"lat_p50_mean": None, "lat_p99_mean": None,
                            "lat_p999_mean": None, "lat_p99_max": None})
            row["arrived_mean"] = round(float(self.arrived.mean()), 3)
        return row

    def trace_records(self) -> List[Dict]:
        """The fleet's per-trial trace plane decoded to FLEET-STACKED
        records (per-round dicts whose counters are per-trial LISTS —
        the format `obs.check_recovery` verdicts per trial on).  Rows
        are ordered by construction; no re-sort needed."""
        if self.trace is None:
            raise ValueError(
                "this fleet ran without the trace plane — set "
                "cfg.trace_every > 0 to capture per-trial round-by-"
                "round traces (obs/trace.py)")
        from go_avalanche_tpu.obs import trace as trace_mod

        return trace_mod.fleet_trace_records(self.trace)

    def realizations(self) -> Dict:
        """JSON-ready per-trial stochastic fault realizations for the
        phase-diagram row: ``{"cut": [[[start, end, split], ...] per
        trial], "spike": [[[start, end, extra], ...] per trial]}``;
        {} when the script schedules no stochastic events."""
        out: Dict = {}
        if self.cut_windows is not None and self.cut_windows.shape[1]:
            cuts = np.concatenate(
                [self.cut_windows,
                 self.cut_split[:, :, None]], axis=2)
            out["cut"] = cuts.astype(int).tolist()
        if self.spike_windows is not None and self.spike_windows.shape[1]:
            out["spike"] = self.spike_windows.astype(int).tolist()
        if (self.region_windows is not None
                and self.region_windows.shape[1]):
            out["region"] = self.region_windows.astype(int).tolist()
        return out


def run_fleet(
    model: str,
    cfg: AvalancheConfig,
    fleet: int,
    n_nodes: int,
    n_txs: int = 64,
    n_rounds: int = 100,
    seed: int = 0,
    conflict_size: int = 2,
    yes_fraction: float = 0.5,
    contested: bool = True,
    window: int = 64,
    mesh=None,
) -> FleetResult:
    """Run `fleet` independent trials of one config point as ONE
    vmapped program; reduce to Wilson-CI estimates.

    Per-trial keys are `split(key(seed), fleet)`, so trial i of a fleet
    is deterministic in (config, seed, fleet) and trials never share a
    stream.  `contested` (avalanche only) seeds per-node 50/50 priors
    from each trial's key — the convergence workload; `yes_fraction`
    is the snowball prior; `window` (backlog only) is the streaming
    working-set slot count — with `cfg.arrivals_enabled()` each trial
    realizes its own arrival stream and reports finality-latency
    percentiles, which is what lets a phase grid sweep OFFERED LOAD
    (`arrival_rate`) into a capacity diagram.

    `mesh` (a `parallel.sharded_fleet.make_fleet_mesh` mesh) lays the
    TRIAL axis across its devices — D devices each run F/D whole sims
    in one compiled program, bit-identical to the dense fleet on the
    same seeds (per-trial keys, vectors, realizations and traces are
    the dense ones, reassembled in key order; the psum'd in-graph
    summary counts are cross-checked against them).  F must divide by
    the device count; a 1-device mesh collapses to the dense program.
    """
    if model not in FLEET_MODELS:
        raise ValueError(f"fleet models are {', '.join(FLEET_MODELS)}, "
                         f"got {model!r}")
    if cfg.arrivals_enabled() and model != "backlog":
        raise ValueError(
            f"the live-traffic arrival plane only threads through the "
            f"backlog fleet model; with model {model!r} the arrival "
            f"config is inert and every trial would be mislabeled "
            f"'{cfg.arrival_mode}-arrival'")
    if cfg.arrival_mode == "external":
        raise ValueError(
            "arrival_mode 'external' has no push path inside the "
            "vmapped fleet program (arrivals come only from "
            "traffic.push_arrivals) — every trial would run an empty "
            "stream and report nothing settled; use a schedule mode "
            "for fleet offered-load sweeps")
    if fleet < 1:
        raise ValueError(f"fleet must be >= 1, got {fleet}")
    if cfg.stake_mode != "off" and model == "snowball":
        raise ValueError(
            "the snowball model samples peers uniformly (no "
            "latency_weight plane), so a stake config is inert there "
            "and every trial would be mislabeled "
            f"'{cfg.stake_mode}-stake' — use the avalanche/dag/backlog "
            "models for stake-weighted committee fleets")
    if cfg.registry_nodes > 0:
        raise ValueError(
            "the node registry (cfg.registry_nodes) is the node-stream "
            "scheduler's axis (models/node_stream), which no fleet "
            "model runs — av.init deliberately skips the stake fold "
            "under the registry, so every trial would draw UNIFORM "
            f"peers while tagged 'registry{cfg.registry_nodes}/"
            f"{cfg.active_nodes}'; a fleet node_stream model is the "
            "open ROADMAP follow-up (million-node axis, next steps)")
    if cfg.metrics_every > 0:
        raise ValueError(
            "the in-graph metrics tap (cfg.metrics_every > 0) cannot "
            "run under the fleet vmap — an io_callback has no per-trial "
            "identity there; phase rows stream host-side through the "
            "obs sink instead")
    if model == "dag" and n_txs % conflict_size:
        raise ValueError(f"n_txs ({n_txs}) must divide by conflict_size "
                         f"({conflict_size})")
    from go_avalanche_tpu.parallel import sharded_fleet

    sharded = sharded_fleet.mesh_devices(mesh) > 1
    if sharded:
        sharded_fleet.check_fleet_divisible(fleet, mesh)
    keys = jax.random.split(jax.random.key(seed), fleet)
    program = compiled_fleet_program(
        model, cfg, n_nodes, n_txs, n_rounds, conflict_size,
        yes_fraction, contested, window, mesh=mesh)
    counts = None
    if sharded:
        outcome, counts, telemetry, trace_buf = program(keys)
    else:
        outcome, telemetry, trace_buf = program(keys)
    violations = np.asarray(jax.device_get(outcome.violation))
    settled = np.asarray(jax.device_get(outcome.settled))
    stalled = np.asarray(jax.device_get(outcome.stalled))
    finality = np.asarray(jax.device_get(outcome.finality_round))
    frac = np.asarray(jax.device_get(outcome.finalized_fraction))
    cut_windows = cut_split = spike_windows = region_windows = None
    if outcome.cut_start is not None:
        cut_windows = np.stack(
            [np.asarray(jax.device_get(outcome.cut_start)),
             np.asarray(jax.device_get(outcome.cut_end))], axis=-1)
        cut_split = np.asarray(jax.device_get(outcome.cut_split))
        spike_windows = np.stack(
            [np.asarray(jax.device_get(outcome.spike_start)),
             np.asarray(jax.device_get(outcome.spike_end)),
             np.asarray(jax.device_get(outcome.spike_extra))], axis=-1)
        region_windows = np.stack(
            [np.asarray(jax.device_get(outcome.region_start)),
             np.asarray(jax.device_get(outcome.region_end)),
             np.asarray(jax.device_get(outcome.region_cluster))],
            axis=-1)
    if counts is not None:
        # The sharded fleet's psum'd in-graph summary counts vs the
        # all-gathered per-trial vectors (the PR-8 sharded
        # self-consistency pattern): a mismatch means the trial gather
        # reordered or dropped a trial — fail loudly rather than emit a
        # phase row whose counts and vectors disagree.
        got = {"trials": int(jax.device_get(counts.trials)),
               "violations": int(jax.device_get(counts.violations)),
               "settled": int(jax.device_get(counts.settled)),
               "stalled": int(jax.device_get(counts.stalled))}
        want = {"trials": fleet, "violations": int(violations.sum()),
                "settled": int(settled.sum()),
                "stalled": int(stalled.sum())}
        if got != want:
            raise RuntimeError(
                f"sharded-fleet summary counts diverged from the "
                f"gathered per-trial vectors: psum'd {got} vs gathered "
                f"{want} — the trial axis lost its identity "
                f"(parallel/sharded_fleet.py)")
    lat_percentiles = arrived = None
    if outcome.lat_p50 is not None:
        lat_percentiles = np.stack(
            [np.asarray(jax.device_get(outcome.lat_p50)),
             np.asarray(jax.device_get(outcome.lat_p99)),
             np.asarray(jax.device_get(outcome.lat_p999))], axis=-1)
        arrived = np.asarray(jax.device_get(outcome.arrived))

    res = FleetResult(
        model=model, fleet=fleet, rounds=n_rounds,
        violations=violations, settled=settled, finality_round=finality,
        finalized_fraction=frac, stalled=stalled,
        telemetry=jax.device_get(telemetry),
        cut_windows=cut_windows, cut_split=cut_split,
        spike_windows=spike_windows, region_windows=region_windows,
        lat_percentiles=lat_percentiles, arrived=arrived,
        trace=(None if trace_buf is None
               else jax.device_get(trace_buf)),
        p_violation=float(violations.mean()),
        violation_ci=wilson_interval(int(violations.sum()), fleet),
        p_settled=float(settled.mean()),
        settled_ci=wilson_interval(int(settled.sum()), fleet),
        p_stall=float(stalled.mean()),
        stall_ci=wilson_interval(int(stalled.sum()), fleet),
    )
    if settled.any():
        fr = finality[settled].astype(np.float64)
        res.finality_mean = float(fr.mean())
        half = (float(1.96 * fr.std(ddof=1) / math.sqrt(fr.size))
                if fr.size > 1 else 0.0)
        res.finality_ci = (res.finality_mean - half,
                           res.finality_mean + half)
    return res


def fleet_trace_records(telemetry, fleet: int) -> List[Dict]:
    """A fleet's stacked telemetry (`[F, R]` leaves) as FLEET-STACKED
    trace records: one dict per round whose counter values are
    per-trial LISTS — the format `obs.recovery.check_recovery`
    dispatches on (and the `--metrics` JSONL spelling of a fleet run,
    docs/observability.md)."""
    from go_avalanche_tpu.obs.sink import _flatten_telemetry

    flat = _flatten_telemetry(jax.device_get(telemetry), {})
    n_rounds = int(next(iter(flat.values())).shape[1])
    return [{"round": r,
             **{k: [int(v[i, r]) for i in range(fleet)]
                for k, v in flat.items()}}
            for r in range(n_rounds)]


# --------------------------------------------------------------------------
# Phase grids: config axes swept by re-jit, one fleet per point.

# Axis name -> coercion.  The sweepable axes are exactly the papers'
# (k, quorum, byzantine fraction, adversary strategy) plus the fault /
# latency knobs a phase diagram wants on its other axis.
_GRID_AXES = {
    "k": int,
    "quorum": int,
    "window": int,
    "alpha": float,
    "finalization_score": int,
    "byzantine_fraction": float,
    "flip_probability": float,
    "drop_probability": float,
    "churn_probability": float,
    "latency_rounds": int,
    "adversary_strategy": str,
    "adversary_policy": str,
    "arrival_rate": float,
    "stake_zipf_s": float,
}


def phase_points(grid: Dict) -> List[Dict]:
    """Validate a phase-grid spec and expand it to the cartesian list
    of config-override points.

    A grid is ``{axis: [value, ...], ...}`` with axes from
    `_GRID_AXES`; entries must be numeric (strings only for
    `adversary_strategy` and `adversary_policy`).  Raises `ValueError` with the offending
    axis/index — `run_sim --phase-grid` funnels this into
    `parser.error` (the PR 5 rule: a malformed sweep dies at the
    parser, never in the worker).
    """
    if not isinstance(grid, dict) or not grid:
        raise ValueError("a phase grid is a non-empty JSON object "
                         "{axis: [values...]}")
    axes, levels = [], []
    for axis, values in grid.items():
        if axis not in _GRID_AXES:
            raise ValueError(
                f"unknown phase-grid axis {axis!r}; sweepable axes: "
                f"{', '.join(sorted(_GRID_AXES))}")
        if not isinstance(values, (list, tuple)) or not values:
            raise ValueError(
                f"phase-grid axis {axis!r} needs a non-empty list of "
                f"values, got {values!r}")
        coerce = _GRID_AXES[axis]
        coerced = []
        for i, v in enumerate(values):
            if coerce is str:
                if not isinstance(v, str):
                    raise ValueError(
                        f"phase-grid {axis}[{i}] must be a "
                        f"{'policy' if axis == 'adversary_policy' else 'strategy'}"
                        f" name, got {v!r}")
                if axis == "adversary_policy":
                    if v not in ADVERSARY_POLICIES:
                        raise ValueError(
                            f"phase-grid {axis}[{i}]: unknown adversary "
                            f"policy {v!r}; policies: "
                            f"{', '.join(ADVERSARY_POLICIES)}")
                    coerced.append(v)
                else:
                    coerced.append(AdversaryStrategy(v).value)
            else:
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    raise ValueError(
                        f"phase-grid {axis}[{i}] must be numeric, "
                        f"got {v!r}")
                if coerce is int and int(v) != v:
                    # A truncated 8.5 would silently measure (and
                    # label) the k=8 point — reject, don't round.
                    raise ValueError(
                        f"phase-grid {axis}[{i}] must be an integer, "
                        f"got {v!r}")
                coerced.append(coerce(v))
        axes.append(axis)
        levels.append(coerced)
    return [dict(zip(axes, combo))
            for combo in itertools.product(*levels)]


def check_adversary_grid(grid: Dict, *, byz_base: float,
                         strategy_base: str, flip_base: float,
                         policy_base: str, async_base: bool,
                         stake_base: str = "off",
                         margin_base: int = 1) -> None:
    """Inert-combination rejections for the adversary phase axes — THE
    one spelling, shared by `run_phase_grid` and the `run_sim
    --phase-grid` parser (a drifted copy would let a sweep die
    mid-grid on a point config's own validator instead of upfront).

    A grid is a cartesian product, so a `byzantine_fraction` axis
    containing 0 combines with EVERY adversary-knob value — any
    non-default knob (from another axis or the base config) would make
    the 0 points reject at construction (`_validate_adversary`), so
    the whole combination is rejected here before the first point
    compiles.  Likewise a `timing` policy point needs the base
    config's async engine (the policy rides the latency plane, which
    no phase axis can turn on).
    """
    byz = grid.get("byzantine_fraction", [byz_base])
    policies = grid.get("adversary_policy", [policy_base])
    strategies = grid.get("adversary_strategy", [strategy_base])
    flips = grid.get("flip_probability", [flip_base])
    knobs = []
    if any(p != "off" for p in policies):
        knobs.append("adversary_policy")
    if any(st != AdversaryStrategy.FLIP.value for st in strategies):
        knobs.append("adversary_strategy")
    if any(f != 1.0 for f in flips):
        knobs.append("flip_probability")
    if knobs and any(b == 0.0 for b in byz):
        raise ValueError(
            f"the grid combines byzantine_fraction == 0 points with "
            f"{'/'.join(knobs)} set: with no byzantine nodes every "
            f"adversary knob is inert, so those points would reject at "
            f"construction — sweep byzantine_fraction over non-zero "
            f"values (the 2409.02217 phase boundary starts above 0), "
            f"or drop the adversary axes")
    if any(p == "timing" for p in policies) and not async_base:
        raise ValueError(
            "an adversary_policy 'timing' point needs the base "
            "config's async engine (a latency_mode or a scheduled "
            "cut/spike): the policy delays lies through the in-flight "
            "latency plane, which no phase axis can turn on")
    if any(p == "stake_eclipse" for p in policies) and stake_base == "off":
        raise ValueError(
            "an adversary_policy 'stake_eclipse' point needs the base "
            "config's stake_mode set (the eclipse set derives from the "
            "stake plane, which no phase axis can turn on)")
    if (margin_base != 1
            and any(p != "withhold_near_quorum" for p in policies)):
        raise ValueError(
            "the base config's adversary_margin is non-default but the "
            "grid includes adversary_policy points other than "
            "'withhold_near_quorum' — those points would reject the "
            "margin as inert at construction")
    if (any(p == "split_vote" for p in policies)
            and any(st != AdversaryStrategy.FLIP.value
                    for st in strategies)):
        raise ValueError(
            "the grid combines adversary_policy 'split_vote' points "
            "with a non-default adversary_strategy: split_vote "
            "OVERRIDES the lie content, so those points would reject "
            "the strategy as silently ignored at construction")


def point_config(base_cfg: AvalancheConfig, point: Dict) -> AvalancheConfig:
    """`base_cfg` with one phase point's overrides applied (validated by
    the config's own `__post_init__`)."""
    overrides = dict(point)
    if "adversary_strategy" in overrides:
        overrides["adversary_strategy"] = AdversaryStrategy(
            overrides["adversary_strategy"])
    return dataclasses.replace(base_cfg, **overrides)


def run_phase_grid(
    model: str,
    base_cfg: AvalancheConfig,
    grid: Dict,
    fleet: int,
    n_nodes: int,
    n_txs: int = 64,
    n_rounds: int = 100,
    seed: int = 0,
    conflict_size: int = 2,
    yes_fraction: float = 0.5,
    contested: bool = True,
    window: int = 64,
    sink=None,
    mesh=None,
) -> List[Dict]:
    """Sweep a phase grid: one `run_fleet` per cartesian point (re-jit
    per point — the config is jit-static), returning one summary row
    per point and streaming each to `sink` (an `obs.MetricsSink`) as it
    lands — the phase-diagram JSONL, each row carrying its `point`,
    the fleet estimates, the per-trial REALIZED stochastic fault
    schedules (`FleetResult.realizations`; absent without stochastic
    events), and the point config's `tag_from_config` tag.  `mesh`
    lays every point's trial axis across a fleet mesh (`run_fleet`);
    rows are bit-identical to the dense sweep's.
    """
    from go_avalanche_tpu.obs import tag_from_config

    points = phase_points(grid)
    if (base_cfg.latency_mode == "none"
            and any("latency_rounds" in p for p in points)):
        # The knob is inert without a latency mode: the sweep would
        # emit identical measurements labeled as different points —
        # the silent-mislabeling class phase_points already rejects
        # for truncated integers.
        raise ValueError(
            "a latency_rounds phase axis needs the base config's "
            "latency_mode set (it is 'none', under which the knob is "
            "inert — every point would measure the same program)")
    if any("arrival_rate" in p for p in points):
        # Same inert-knob class as latency_rounds: fail with the
        # sweep-level message before the first point compiles.
        if not base_cfg.arrivals_enabled():
            raise ValueError(
                "an arrival_rate phase axis needs the base config's "
                "arrival_mode set (it is 'off', under which the knob is "
                "inert — offered-load sweeps need a live-traffic "
                "schedule)")
        if model != "backlog":
            raise ValueError(
                f"an arrival_rate phase axis needs the backlog fleet "
                f"model (the traffic plane is not threaded through "
                f"{model!r} — every point would measure the same "
                f"program)")
    check_adversary_grid(
        grid, byz_base=base_cfg.byzantine_fraction,
        strategy_base=base_cfg.adversary_strategy.value,
        flip_base=base_cfg.flip_probability,
        policy_base=base_cfg.adversary_policy,
        async_base=base_cfg.async_queries(),
        stake_base=base_cfg.stake_mode,
        margin_base=base_cfg.adversary_margin)
    if (base_cfg.stake_mode != "zipf"
            and any("stake_zipf_s" in p for p in points)):
        # Same inert-knob class as latency_rounds: under any other
        # stake mode the exponent is rejected (or ignored) per point —
        # fail with the sweep-level message before the first point
        # compiles.
        raise ValueError(
            "a stake_zipf_s phase axis needs the base config's "
            "stake_mode set to 'zipf' (the exponent is only read "
            "there — every point would otherwise reject or measure "
            "the same program)")
    from go_avalanche_tpu.analysis import retrace

    rows = []
    cache = _fleet_cache(mesh)
    for point in points:
        cfg = point_config(base_cfg, point)
        # One compile per config point is the fleet's whole
        # dispatch-amortization premise (PR 7): the active fleet cache
        # (dense or mesh-keyed sharded — `_fleet_cache`) may TRACE at
        # most once per point (zero for a repeated point — lru hit).
        # More means the config stopped being a stable jit-static
        # cache key; fail the sweep rather than silently recompile per
        # trial batch (analysis/retrace.py).
        misses_before = cache.cache_info().misses
        res = run_fleet(model, cfg, fleet, n_nodes, n_txs=n_txs,
                        n_rounds=n_rounds, seed=seed,
                        conflict_size=conflict_size,
                        yes_fraction=yes_fraction, contested=contested,
                        window=window, mesh=mesh)
        retrace.guard_fleet_point(
            misses_before, cache.cache_info().misses, point)
        row = {"point": point, **res.summary(),
               "tag": tag_from_config(cfg)}
        realized = res.realizations()
        if realized:
            row["realizations"] = realized
        rows.append(row)
        if sink is not None:
            sink.write(row)
    return rows
