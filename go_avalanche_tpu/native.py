"""ctypes bindings to the native C++ host runtime (`native/avalanche_host`).

The control plane of the framework is available in two interchangeable
implementations: the pure-Python `Processor` (`processor.py`) and this native
`libavalanche_host.so` (C++17, std::thread ticker), both with full reference
parity (`processor.go:11-248`, SURVEY.md §2.3) and both tested against the
same golden vectors.  The native runtime is for host-side deployments where
the per-query Python overhead matters (e.g. the Connector service fanning out
to thousands of external harness connections); the JAX simulators remain the
TPU compute path either way.

The library is built by `make -C native` (g++ only, no deps); `ensure_built`
does this on demand.  No pybind11 in this image, hence ctypes (C ABI in
`native/avalanche_host/capi.cc`).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence, Tuple

from go_avalanche_tpu.config import AvalancheConfig, DEFAULT_CONFIG
from go_avalanche_tpu.types import (
    Response,
    Status,
    StatusUpdate,
    Vote,
    normalize_err,
)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libavalanche_host.so")

_lib: Optional[ctypes.CDLL] = None


class NativeBuildError(RuntimeError):
    """The native library could not be built/loaded."""


def ensure_built(force: bool = False) -> str:
    """Build `libavalanche_host.so`; returns its path.

    Always invokes make — its dependency tracking makes this a no-op when
    the library is newer than the sources, and it means edited C++ sources
    are never silently served stale to a fresh process.  `force` does a
    clean rebuild.
    """
    try:
        if force:
            subprocess.run(["make", "-C", _NATIVE_DIR, "clean"],
                           check=True, capture_output=True, text=True)
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, "all"],
            check=True, capture_output=True, text=True)
    except FileNotFoundError as e:
        # No make on this machine: a prebuilt library is the only candidate
        # (and with no toolchain there can be no freshly-edited sources to
        # go stale against it).  Other OSErrors (EACCES, ENOMEM) propagate:
        # a toolchain exists there, so serving a stale .so is the hazard.
        if os.path.exists(_LIB_PATH):
            return _LIB_PATH
        raise NativeBuildError(
            f"no native toolchain and no prebuilt library: {e}") from e
    except (OSError, subprocess.CalledProcessError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        # Raise even when a stale .so exists; silently serving it would run
        # pre-edit code after a broken edit.
        raise NativeBuildError(
            f"building native runtime failed: {detail}") from e
    return _LIB_PATH


def load_library() -> ctypes.CDLL:
    """Load (building on demand) the native runtime; cached.

    There is deliberately no force-reload flag: dlopen caches by path, so a
    rebuilt .so cannot be re-loaded into a process that already mapped it —
    use `ensure_built(force=True)` and a fresh process to pick up changes.
    """
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(ensure_built())

    u32, i32, i64, i8 = (ctypes.c_uint32, ctypes.c_int32, ctypes.c_int64,
                         ctypes.c_int8)
    p_i32, p_i64, p_u32, p_i8 = (ctypes.POINTER(i32), ctypes.POINTER(i64),
                                 ctypes.POINTER(u32), ctypes.POINTER(i8))

    lib.avh_vote_record_new.restype = u32
    lib.avh_vote_record_new.argtypes = [ctypes.c_int]
    lib.avh_vote_record_step.restype = u32
    lib.avh_vote_record_step.argtypes = [
        u32, i32, ctypes.c_int, ctypes.c_int, ctypes.c_int, p_i32]
    lib.avh_vote_record_replay.restype = u32
    lib.avh_vote_record_replay.argtypes = [
        ctypes.c_int, p_i32, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, p_u32, p_i32]

    lib.avh_processor_new.restype = ctypes.c_void_p
    lib.avh_processor_new.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_double, ctypes.c_double, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_uint64]
    lib.avh_processor_free.argtypes = [ctypes.c_void_p]
    lib.avh_set_stub_time.argtypes = [ctypes.c_void_p, ctypes.c_double]
    lib.avh_use_real_clock.argtypes = [ctypes.c_void_p]
    lib.avh_add_node.argtypes = [ctypes.c_void_p, i64]
    lib.avh_node_ids.restype = ctypes.c_int
    lib.avh_node_ids.argtypes = [ctypes.c_void_p, p_i64, ctypes.c_int]
    lib.avh_add_target.restype = ctypes.c_int
    lib.avh_add_target.argtypes = [ctypes.c_void_p, i64, ctypes.c_int,
                                   ctypes.c_int, i64]
    lib.avh_set_target_valid.restype = ctypes.c_int
    lib.avh_set_target_valid.argtypes = [ctypes.c_void_p, i64, ctypes.c_int]
    lib.avh_get_round.restype = i64
    lib.avh_get_round.argtypes = [ctypes.c_void_p]
    lib.avh_is_accepted.restype = ctypes.c_int
    lib.avh_is_accepted.argtypes = [ctypes.c_void_p, i64]
    lib.avh_get_confidence.restype = ctypes.c_int
    lib.avh_get_confidence.argtypes = [ctypes.c_void_p, i64]
    lib.avh_outstanding_requests.restype = ctypes.c_int
    lib.avh_outstanding_requests.argtypes = [ctypes.c_void_p]
    lib.avh_get_invs.restype = ctypes.c_int
    lib.avh_get_invs.argtypes = [ctypes.c_void_p, p_i64, ctypes.c_int]
    lib.avh_suitable_node.restype = i64
    lib.avh_suitable_node.argtypes = [ctypes.c_void_p]
    lib.avh_register_votes.restype = ctypes.c_int
    lib.avh_register_votes.argtypes = [
        ctypes.c_void_p, i64, i64, p_i64, p_i32, ctypes.c_int,
        p_i64, p_i8, ctypes.c_int, p_i32]
    lib.avh_event_loop_tick.restype = ctypes.c_int
    lib.avh_event_loop_tick.argtypes = [ctypes.c_void_p]
    lib.avh_start.restype = ctypes.c_int
    lib.avh_start.argtypes = [ctypes.c_void_p]
    lib.avh_stop.restype = ctypes.c_int
    lib.avh_stop.argtypes = [ctypes.c_void_p]

    _lib = lib
    return lib


class NativeVoteRecord:
    """Scalar vote record backed by the native kernel; oracle-compatible API
    (mirrors `utils.golden.ScalarVoteRecord`)."""

    def __init__(self, accepted: bool,
                 cfg: AvalancheConfig = DEFAULT_CONFIG) -> None:
        self._lib = load_library()
        self._cfg = cfg
        self._state = self._lib.avh_vote_record_new(1 if accepted else 0)

    @property
    def votes(self) -> int:
        return self._state & 0xFF

    @property
    def consider(self) -> int:
        return (self._state >> 8) & 0xFF

    @property
    def confidence(self) -> int:
        return (self._state >> 16) & 0xFFFF

    def is_accepted(self) -> bool:
        return (self.confidence & 1) == 1

    def get_confidence(self) -> int:
        return self.confidence >> 1

    def has_finalized(self) -> bool:
        return self.get_confidence() >= self._cfg.finalization_score

    def register_vote(self, err: int) -> bool:
        changed = ctypes.c_int32(0)
        self._state = self._lib.avh_vote_record_step(
            self._state, normalize_err(err), self._cfg.window,
            self._cfg.quorum, self._cfg.finalization_score,
            ctypes.byref(changed))
        return bool(changed.value)

    def status(self) -> Status:
        fin, acc = self.has_finalized(), self.is_accepted()
        if fin:
            return Status.FINALIZED if acc else Status.INVALID
        return Status.ACCEPTED if acc else Status.REJECTED


def native_replay(accepted: bool, errs: Sequence[int],
                  cfg: AvalancheConfig = DEFAULT_CONFIG,
                  ) -> List[Tuple[int, int, int, bool]]:
    """Replay a vote stream through the native kernel in one C call.

    Same trace format as `utils.golden.replay`:
    per-vote (votes, consider, confidence, changed).
    """
    lib = load_library()
    n = len(errs)
    errs_arr = (ctypes.c_int32 * n)(*[normalize_err(e) for e in errs])
    states = (ctypes.c_uint32 * n)()
    changed = (ctypes.c_int32 * n)()
    lib.avh_vote_record_replay(1 if accepted else 0, errs_arr, n,
                               cfg.window, cfg.quorum, cfg.finalization_score,
                               states, changed)
    return [(int(states[i]) & 0xFF, (int(states[i]) >> 8) & 0xFF,
             (int(states[i]) >> 16) & 0xFFFF, bool(changed[i]))
            for i in range(n)]


class NativeProcessor:
    """The native Processor, method-compatible with `processor.Processor`.

    Differences from the Python twin: targets are registered by their scalar
    attributes (hash / initial preference / validity / score) rather than a
    `Target` object — the native boundary keeps objects on the caller's side;
    `invalidate(hash)` replaces mutating a Target's is_valid.
    """

    def __init__(
        self,
        cfg: AvalancheConfig = DEFAULT_CONFIG,
        advance_round: bool = True,
        node_selection: str = "lowest",
        seed: int = 0,
    ) -> None:
        if node_selection not in ("lowest", "random"):
            raise ValueError("node_selection must be 'lowest' or 'random'")
        self._lib = load_library()
        self._cfg = cfg
        self._handle = self._lib.avh_processor_new(
            cfg.window, cfg.quorum, cfg.finalization_score,
            cfg.max_element_poll, cfg.time_step_s, cfg.request_timeout_s,
            1 if cfg.strict_validation else 0, 1 if advance_round else 0,
            1 if node_selection == "random" else 0, seed)

    def close(self) -> None:
        if self._handle is not None:
            self._lib.avh_stop(self._h())
            self._lib.avh_processor_free(self._h())
            self._handle = None

    def _h(self):
        """Live handle, or a clean error after close() (never pass NULL —
        a closed handle must not reach the C ABI)."""
        if self._handle is None:
            raise RuntimeError("NativeProcessor is closed")
        return self._handle

    def __del__(self) -> None:  # best-effort; prefer close()
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self) -> "NativeProcessor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- clock ------------------------------------------------------------
    def set_stub_time(self, t: float) -> None:
        self._lib.avh_set_stub_time(self._h(), t)

    # --- membership -------------------------------------------------------
    def add_node(self, node_id: int) -> None:
        self._lib.avh_add_node(self._h(), node_id)

    def nodes_ids(self) -> List[int]:
        cap = 4096
        buf = (ctypes.c_int64 * cap)()
        n = self._lib.avh_node_ids(self._h(), buf, cap)
        if n > cap:
            cap = n
            buf = (ctypes.c_int64 * cap)()
            n = self._lib.avh_node_ids(self._h(), buf, cap)
        return [int(buf[i]) for i in range(min(n, cap))]

    # --- admission / state ------------------------------------------------
    def add_target_to_reconcile(self, target_hash: int, accepted: bool,
                                valid: bool = True, score: int = 1) -> bool:
        return bool(self._lib.avh_add_target(
            self._handle, target_hash, 1 if accepted else 0,
            1 if valid else 0, score))

    def invalidate(self, target_hash: int) -> bool:
        return bool(self._lib.avh_set_target_valid(self._h(),
                                                   target_hash, 0))

    def get_round(self) -> int:
        return int(self._lib.avh_get_round(self._h()))

    def is_accepted(self, target_hash: int) -> bool:
        return bool(self._lib.avh_is_accepted(self._h(), target_hash))

    def get_confidence(self, target_hash: int) -> int:
        c = self._lib.avh_get_confidence(self._h(), target_hash)
        if c < 0:
            raise KeyError(f"VoteRecord not found for hash {target_hash}")
        return c

    def outstanding_requests(self) -> int:
        return int(self._lib.avh_outstanding_requests(self._h()))

    # --- polls ------------------------------------------------------------
    def get_invs_for_next_poll(self) -> List[int]:
        cap = max(self._cfg.max_element_poll, 1)
        buf = (ctypes.c_int64 * cap)()
        n = self._lib.avh_get_invs(self._h(), buf, cap)
        return [int(buf[i]) for i in range(min(n, cap))]

    def get_suitable_node_to_query(self) -> int:
        return int(self._lib.avh_suitable_node(self._h()))

    # --- ingest -----------------------------------------------------------
    def register_votes(self, node_id: int, resp: Response,
                       updates: List[StatusUpdate]) -> bool:
        votes: Sequence[Vote] = resp.get_votes()
        n = len(votes)
        hashes = (ctypes.c_int64 * max(n, 1))(*[v.get_hash() for v in votes])
        errs = (ctypes.c_int32 * max(n, 1))(
            *[normalize_err(v.get_error()) for v in votes])
        # At most one status update per vote, so n slots always suffice.
        cap = max(n, 1)
        out_h = (ctypes.c_int64 * cap)()
        out_s = (ctypes.c_int8 * cap)()
        n_up = ctypes.c_int32(0)
        ok = self._lib.avh_register_votes(
            self._h(), node_id, resp.get_round(), hashes, errs, n,
            out_h, out_s, cap, ctypes.byref(n_up))
        for i in range(n_up.value):
            updates.append(StatusUpdate(int(out_h[i]), Status(int(out_s[i]))))
        return bool(ok)

    # --- event loop -------------------------------------------------------
    def event_loop(self) -> bool:
        return bool(self._lib.avh_event_loop_tick(self._h()))

    def start(self) -> bool:
        return bool(self._lib.avh_start(self._h()))

    def stop(self) -> bool:
        return bool(self._lib.avh_stop(self._h()))
